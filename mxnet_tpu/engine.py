"""Dependency engine, TPU-native.

Reference: ``src/engine/`` — an async scheduler with versioned variables
(``include/mxnet/engine.h:44``), per-device worker threads, and
read/write-dependency queues (``src/engine/threaded_engine.h:71-150``).

On TPU the heavy machinery collapses by design: PJRT dispatch is already
asynchronous (every jax op returns a future-backed buffer and executes in
enqueue order on the device stream), so RAW/WAR ordering within a device is
guaranteed by the runtime and there is nothing for a worker thread to do.
What survives from the reference engine, and what this module provides:

* ``Var`` — versioned variables (one per NDArray chunk).  Version bumps on
  every write; this is what makes MXNet-style "mutation" observable and is
  used by the executable caches to invalidate.
* ``push``/``push_async`` — an explicit hand-off point kept so engine-level
  instrumentation (profiler hooks, op bulking stats) has a single choke
  point, and so an alternate threaded implementation can be slotted in via
  ``MXNET_ENGINE_TYPE`` exactly like the reference (``src/engine/engine.cc:32``).
* ``wait_for_var`` / ``wait_for_all`` — blocking sync, incl. async exception
  rethrow (parity: ``src/engine/threaded_engine.cc:383-436``).
* op bulking (``BulkEngine`` / ``bulk(size)``) — the reference's imperative
  segment fusion (``MXNET_EXEC_BULK_EXEC_*``, imperative_utils.h
  ``CreateEngineOpSeg``): consecutive deferrable ops collect into a
  ``BulkSegment`` and flush as ONE jitted, XLA-fused executable at the
  first sync point, so N python/PJRT dispatches collapse into ~1.
"""
from __future__ import annotations

import collections
import itertools
import os
import sys
import threading
import time
import weakref

import jax

from . import compile_cache as _ccache
from .telemetry import flight as _flight
from .telemetry import memdump as _memdump
from .telemetry import metrics as _metrics
from .testing.faults import maybe_inject as _inject

# itertools.count holds the GIL for the whole increment, so ids stay
# unique across threads without a lock on the NDArray hot path
_var_ids = itertools.count(1)

# bound on first use by Engine.bulk_size (importing at module scope would
# cycle: autograd lazily imports the engine for segment flushes)
_autograd = None


class Var:
    """Versioned variable (parity: engine::Var, include/mxnet/engine.h:44)."""

    __slots__ = ("vid", "version", "_exc")

    def __init__(self):
        self.vid = next(_var_ids)
        self.version = 0
        self._exc = None

    def on_write(self):
        self.version += 1

    def set_exception(self, exc):
        self._exc = exc

    def rethrow(self):
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc


class _Stats:
    __slots__ = ("ops_pushed", "bulk_ops", "bulk_segments", "bulk_donated",
                 "sync_origins", "flush_origins")

    def __init__(self):
        self.ops_pushed = 0
        self.bulk_ops = 0       # ops that executed inside a bulk segment
        self.bulk_segments = 0  # segments flushed (each = one push)
        self.bulk_donated = 0   # dead input buffers donated to XLA
        self.sync_origins = {}   # device->host syncs by origin
        self.flush_origins = {}  # segment flushes by origin kind


# ----------------------------------------------------------------------------
# op bulking (reference: MXNET_EXEC_BULK_EXEC_* segments,
# src/imperative/imperative_utils.h CreateEngineOpSeg)
# ----------------------------------------------------------------------------

# jitted segment executables keyed by the op-sequence structure
# (op name, static attrs, argument wiring, donated-input set) — the
# engine-level analogue of CachedOp's executable cache for code that never
# calls hybridize().  jax.jit adds the per-(shape, dtype) level underneath,
# so re-running the same imperative stream with the same avals re-traces
# nothing.  Segments are bucketed into size tiers, each with its own LRU
# budget: short interactive chains (<=8 ops) and long fused training steps
# (<=64) churn at very different rates, and one flat LRU lets a burst of
# small segments evict the expensive long-segment executables.
_SEG_TIER_BOUNDS = (8, 16, 32, 64)
_SEG_TIER_LABELS = ("le8", "le16", "le32", "le64")


def _parse_tier_budgets():
    vals = [128, 64, 32, 32]  # sums to the old flat cap of 256
    raw = os.environ.get("MXNET_EXEC_BULK_SEG_CACHE_BUDGETS", "").strip()
    if raw:
        try:
            parts = [int(p) for p in raw.split(",")]
        except ValueError:
            parts = []
        for i, p in enumerate(parts[: len(vals)]):
            if p > 0:
                vals[i] = p
    return tuple(vals)


_SEG_TIER_BUDGETS = _parse_tier_budgets()
_SEG_TIERS = tuple(collections.OrderedDict() for _ in _SEG_TIER_BOUNDS)
_seg_tier_stats = tuple({"hits": 0, "misses": 0, "evictions": 0}
                        for _ in _SEG_TIER_BOUNDS)
_seg_cache_stats = {"hits": 0, "misses": 0,  # all-tier totals (collector)
                    "disk_hits": 0}  # persistent-cache warm starts
_trace_count = [0]
_SEGMENT_OPS_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def _tier_index(n_ops):
    for i, bound in enumerate(_SEG_TIER_BOUNDS):
        if n_ops <= bound:
            return i
    # explicit bulk(size) scopes may exceed the largest bound; they share
    # the top tier rather than getting an unbounded one
    return len(_SEG_TIER_BOUNDS) - 1


def bulk_trace_count():
    """How many times a bulk segment has been (re)traced by XLA — the
    probe tests use to assert segment-cache hits (no retrace)."""
    return _trace_count[0]


def _build_segment_fn(steps, donate=(), exact=False, example_args=None):
    """One traceable callable running every deferred step in push order.

    ``steps`` is a sequence of ``(run_fn, slots, n_out)``; each slot is
    ``('v', i)`` — the i-th value produced inside the segment — or
    ``('x', i)`` — the i-th external (concrete) input.  All produced
    values are returned so the signature depends only on the op sequence,
    never on which outputs happen to still be referenced at flush time
    (liveness-dependent signatures would make cache hits GC-timing flaky).
    ``donate`` are ext indices whose buffers are dead at flush time; XLA
    may reuse them for outputs (donated inputs are deleted after the call).

    ``exact=True`` compiles ahead-of-time with XLA optimizations off
    (``xla_backend_optimization_level=0``) so every op keeps exactly the
    rounding its standalone eager executable produces.  The default O2
    pipeline fuses across op boundaries (FMA contraction, output
    rematerialization) and drifts intermediates off eager by ulps — it
    even strips ``optimization_barrier`` before fusing, so barriers can't
    pin the numerics.  Recorded segments need the exact path because the
    tape re-linearizes against segment intermediates and bulked grads
    must be BIT-identical to eager; unrecorded segments keep the fast
    fused path (the forward values the user sees are checked against
    eager by tier-1 at default opts).  The dispatch win (one push per N
    ops) is identical either way.
    """
    steps = tuple(steps)

    def _body(ext):
        _trace_count[0] += 1  # python body → runs only while tracing
        vals = []
        for run_fn, slots, _n_out in steps:
            args = [vals[i] if kind == "v" else ext[i] for kind, i in slots]
            vals.extend(run_fn(*args))
        return tuple(vals)

    if not exact:
        def seg_run(*ext):
            return _body(ext)

        return jax.jit(seg_run, donate_argnums=donate)

    # distinct traced-function NAME for the exact path: the HLO module name
    # enters jax's persistent-cache key, so O0 (taped) and O2 (fused)
    # artifacts for the same op sequence can never cross-hit on disk even
    # if a jax version ever drops compiler_options from the key
    def seg_run_exact_o0(*ext):
        return _body(ext)

    jitted = jax.jit(seg_run_exact_o0, donate_argnums=donate)
    return jitted.lower(*example_args).compile(
        compiler_options={"xla_backend_optimization_level": 0})


class _BulkRef:
    """A promised output of a not-yet-flushed segment (lazy NDArray chunk)."""

    __slots__ = ("segment", "index", "aval", "value", "failed")

    def __init__(self, segment, index, aval):
        self.segment = segment
        self.index = index
        self.aval = aval      # jax.ShapeDtypeStruct from eval_shape
        self.value = None     # concrete jax.Array after flush
        self.failed = False   # the flush raised; value will never arrive


class BulkSegment:
    """A deferred run of consecutive imperative ops, flushed as ONE push.

    Built by ``ops.registry`` (which owns op semantics: attrs, fields,
    eval_shape) and executed here (which owns scheduling: the single
    ``Engine.push``, var poisoning, stats, inflight tracking).
    """

    __slots__ = ("engine", "cap", "steps", "key_parts", "ext", "_ext_ids",
                 "ext_src", "refs", "write_vars", "flushed", "n_ops", "taped")

    def __init__(self, engine, cap):
        self.engine = engine
        self.cap = cap            # flush after this many ops (0 = unbounded)
        self.steps = []           # (run_fn, slots, n_out)
        self.key_parts = []       # hashable mirror of steps → cache key
        self.ext = []             # external concrete inputs, dedup by id
        self._ext_ids = {}
        self.ext_src = []         # per ext: [(weakref(NDArray), Var, version)]
        self.refs = []            # _BulkRef per produced value, in order
        self.write_vars = []      # Vars of every NDArray built on a ref
        self.flushed = False
        self.n_ops = 0
        self.taped = False        # any op recorded into the autograd tape

    def defer(self, step_key, run_fn, handles, out_avals):
        """Append one op; ``handles`` are ``('v', _BulkRef)`` for values
        produced earlier in this segment or ``('x', jax.Array, NDArray)``
        for concrete inputs (the NDArray that supplied the buffer, or
        ``None`` — supplier identity drives input-buffer donation).
        Returns one ``_BulkRef`` per output."""
        slots = []
        for h in handles:
            if h[0] == "v":
                slots.append(("v", h[1].index))
            else:
                v = h[1]
                i = self._ext_ids.get(id(v))
                if i is None:
                    i = len(self.ext)
                    self.ext.append(v)
                    self._ext_ids[id(v)] = i
                    self.ext_src.append([])
                owner = h[2] if len(h) > 2 else None
                src = self.ext_src[i]
                if owner is None:
                    self.ext_src[i] = None  # unknown supplier: never donate
                elif src is not None:
                    try:
                        src.append((weakref.ref(owner), owner._var,
                                    owner._var.version))
                    except TypeError:  # unweakrefable supplier: never donate
                        self.ext_src[i] = None
                slots.append(("x", i))
        slots = tuple(slots)
        base = len(self.refs)
        refs = [_BulkRef(self, base + j, aval)
                for j, aval in enumerate(out_avals)]
        self.refs.extend(refs)
        self.steps.append((run_fn, slots, len(out_avals)))
        self.key_parts.append((step_key, slots, len(out_avals)))
        self.n_ops += 1
        return refs

    def add_write_vars(self, new_vars):
        self.write_vars.extend(new_vars)

    def _donation(self, eng):
        """Ext indices whose buffers are provably dead → XLA donation.

        An ext buffer is donatable iff (a) every NDArray that ever
        supplied it has moved on (collected, or its engine var version
        bumped past the supply-time version — in-place ``out=`` adoption
        and rebinding both land here), (b) its aval matches some segment
        output (XLA can only reuse matching buffers; anything else would
        warn and donate for nothing), and (c) a refcount audit shows no
        OTHER owner: exactly the ext list, the local probe, and tracked
        in-flight occurrences hold it.  (c) is the safety net — buffer
        shares the suppliers can't see (detach/copy views, autograd tape
        primals, another thread's segment) all show up as extra refs and
        veto the donation, so a donated buffer can never be read again.
        """
        if not eng._bulk_donate:
            return ()
        donate = []
        out_avals = None
        inflight = None
        for i, srcs in enumerate(self.ext_src):
            if not srcs:  # None (opted out) or no recorded supplier
                continue
            dead = True
            for wref, var, ver in srcs:
                nd = wref()
                if nd is not None and var.version == ver:
                    dead = False
                    break
            if not dead:
                continue
            b = self.ext[i]
            if out_avals is None:
                out_avals = {(tuple(r.aval.shape), r.aval.dtype)
                             for r in self.refs}
            if (tuple(b.shape), b.dtype) not in out_avals:
                continue
            if inflight is None:
                inflight = collections.Counter(map(id, eng._inflight))
            # refs: ext list + local ``b`` + getrefcount's argument,
            # plus tracked in-flight entries
            if sys.getrefcount(b) <= 3 + inflight[id(b)]:
                donate.append(i)
        if donate:
            # the donated buffers are deleted by the call; purge them from
            # the in-flight ring so waitall() never blocks on a dead buffer
            donated_ids = {id(self.ext[i]) for i in donate}
            eng._inflight = collections.deque(
                d for d in eng._inflight if id(d) not in donated_ids)
        return tuple(donate)

    def flush(self, origin="flush"):
        """Execute the whole segment as one engine push. Idempotent.

        On failure every unresolved ref is marked dead and every output
        var poisoned via ``Var.set_exception`` — the same async-rethrow
        contract the eager path gives a single failing op.
        """
        if self.flushed:
            return
        self.flushed = True
        eng = self.engine
        st = eng._bulk_state()
        if st.seg is self:
            st.seg = None
        if not self.steps:
            return
        donate = self._donation(eng)
        # taped segments compile ahead-of-time (see _build_segment_fn), so
        # their cache key must pin the concrete ext avals jit would have
        # re-traced on; untaped segments let jit handle shape polymorphism.
        # Placement is ALWAYS part of the key: a jax sharding object
        # (SingleDeviceSharding or NamedSharding) encodes platform, device
        # and partition spec, so a segment traced against cpu:0 inputs can
        # never serve sharded (or other-device) inputs — the exact path
        # pins its lowering at build time and would silently compute on
        # the wrong placement otherwise.
        exact = self.taped
        placements = tuple(getattr(a, "sharding", None) for a in self.ext)
        key = (tuple(self.key_parts), donate, placements, exact and tuple(
            (tuple(a.shape), str(a.dtype)) for a in self.ext))
        ti = _tier_index(self.n_ops)
        tier = _SEG_TIERS[ti]
        tstats = _seg_tier_stats[ti]
        # snapshot BEFORE the cache lookup: exact (taped) segments trace
        # at build time inside _build_segment_fn, not at first call
        n_traces0 = _trace_count[0]
        n_disk0 = _ccache.persistent_hits()
        t_flush0 = time.perf_counter()
        fn = tier.get(key)
        cached = fn is not None
        if fn is None:
            tstats["misses"] += 1
            _seg_cache_stats["misses"] += 1
            fn = _build_segment_fn(self.steps, donate, exact=exact,
                                   example_args=self.ext)
            tier[key] = fn
            budget = _SEG_TIER_BUDGETS[ti]
            while len(tier) > budget:
                tier.popitem(last=False)
                tstats["evictions"] += 1
        else:
            tstats["hits"] += 1
            _seg_cache_stats["hits"] += 1
            tier.move_to_end(key)
        _flight.record("engine.flush", origin=origin, ops=self.n_ops,
                       tier=_SEG_TIER_LABELS[ti], cached=cached,
                       donated=len(donate))
        ext = self.ext
        try:
            # one push for the whole op stream; write-var versions were
            # already bumped at defer time (exactly as eager would have),
            # so the push declares none — it only publishes values.
            vals = eng.push(lambda: fn(*ext),
                            op_name="bulk_segment[%d]" % self.n_ops)
        except Exception as e:
            # push() already recorded engine.poison + crash-dumped; this
            # event names the segment-level blast radius
            _flight.record("engine.flush_failed", origin=origin,
                           ops=self.n_ops, error=type(e).__name__,
                           writes=len(self.write_vars))
            for r in self.refs:
                if r.value is None:
                    r.failed = True
            for v in self.write_vars:
                v.set_exception(e)
            self._release()
            raise
        eng.stats.bulk_segments += 1
        eng.stats.bulk_donated += len(donate)
        if _metrics.enabled():
            # origins like "rng:<op>" truncate to "rng" so the metric
            # label set stays bounded (docs/observability.md)
            kind = origin.split(":", 1)[0]
            fo = eng.stats.flush_origins
            fo[kind] = fo.get(kind, 0) + 1
            _metrics.histogram(
                "mxnet_engine_bulk_segment_ops",
                help="ops fused per flushed bulk segment",
                buckets=_SEGMENT_OPS_BUCKETS).observe(self.n_ops)
            retraces = _trace_count[0] - n_traces0
            if retraces:
                if _ccache.persistent_hits() - n_disk0 >= retraces:
                    # the executable came off the persistent disk cache: a
                    # warm start, not a retrace.  Count it as a cache hit
                    # (the disk tier below the in-memory _SEG_TIERS) and
                    # keep it out of mxnet_compile_seconds AND the
                    # MXNET_RETRACE_WARN_THRESHOLD watchdog — a restarted
                    # fleet re-tracing every segment once is healthy.
                    _seg_cache_stats["disk_hits"] += retraces
                else:
                    # first run of a (structure, avals) pair: the push wall
                    # time is trace+compile dominated — record it per retrace
                    _metrics.record_compile(
                        "bulk_segment", ("bulk_segment", key),
                        time.perf_counter() - t_flush0, n=retraces)
        for r, val in zip(self.refs, vals):
            r.value = val
        eng.track_many(vals)
        self._release()

    def _release(self):
        """Drop input/step references once flushed: lazy tape nodes and
        lazy NDArrays can pin _BulkRefs (→ this segment) long after the
        flush, and holding every ext buffer alive through them would keep
        whole training steps' worth of inputs resident.

        ``refs`` is dropped too: each _BulkRef.value pins one output
        buffer, and live consumers (lazy NDArrays, tape nodes) hold their
        OWN _BulkRef — the segment's list is a pure duplicate.  Keeping it
        would add one refcount to every output for the segment cache's
        lifetime, so a segment-N output fed to segment N+1 as a dead ext
        input could never pass the _donation refcount audit — exactly the
        steady-state KV-update shape of a decode loop."""
        self.steps = ()
        self.key_parts = ()
        self.ext = ()
        self._ext_ids = None
        self.ext_src = ()
        self.write_vars = ()
        self.refs = ()


class Engine:
    """Engine façade. ``NaiveEngine`` semantics: push == run-on-device-stream.

    The device stream itself is async (PJRT), so even the "naive" engine gives
    compute/host overlap — the property the reference needed worker threads
    for.  Tracked arrays register their backing buffers so ``wait_for_all``
    can block on everything in flight.
    """

    _instance = None

    def __init__(self):
        self.stats = _Stats()
        self._hooks = []  # profiler hooks: fn(op_name, t_start, t_end)
        self._sync_hooks = []  # sync hooks: fn(origin) per device->host sync
        self.kind = os.environ.get("MXNET_ENGINE_TYPE", "BulkEngine")
        self._inflight = collections.deque()  # recent output buffers (ring)
        self._inflight_cap = int(os.environ.get("MXNET_ENGINE_INFLIGHT_CAP", "512"))
        # op bulking knobs (reference: MXNET_EXEC_BULK_EXEC_*,
        # docs/env_vars.md) — segments are per-thread.
        #
        # Concurrency contract (CD11xx / docs/static_analysis.md): the
        # engine owns NO locks by design.  Mutable state is either
        # per-thread (this threading.local), append-only counters read
        # for monitoring, or var version/pending maps whose cross-thread
        # discipline EngineAudit (MXNET_ENGINE_AUDIT=1) checks at every
        # push — serialization is the caller's (stream's) job, exactly
        # like the reference engine's per-var queues.  Keep it that way:
        # a lock on the push path would serialize dispatch against the
        # device and show up directly in mxnet_lock_hold_seconds.
        self._bulk_tls = threading.local()
        self._bulk_train = os.environ.get(
            "MXNET_EXEC_BULK_EXEC_TRAIN", "1") not in ("", "0")
        self._bulk_infer = os.environ.get(
            "MXNET_EXEC_BULK_EXEC_INFERENCE", "1") not in ("", "0")
        self._bulk_max = int(os.environ.get(
            "MXNET_EXEC_BULK_EXEC_MAX_NODE", "64"))
        self._bulk_donate = os.environ.get(
            "MXNET_EXEC_BULK_DONATE", "1") not in ("", "0")
        # profiling normally disables implicit bulking (per-op spans,
        # reference parity); MXNET_PROFILE_BULK=1 keeps segments fused so
        # the profiler sees the execution mode it is actually measuring
        self._profile_bulk = os.environ.get(
            "MXNET_PROFILE_BULK", "0") not in ("", "0")
        self._audit = None  # EA4xx dependency auditor (docs/static_analysis.md)
        if os.environ.get("MXNET_ENGINE_AUDIT", "0") not in ("", "0"):
            from .analysis.engine_audit import EngineAudit
            self._audit = EngineAudit()

    @staticmethod
    def get():
        if Engine._instance is None:
            Engine._instance = Engine()
        return Engine._instance

    # -- push -------------------------------------------------------------
    def push(self, fn, read_vars=(), write_vars=(), op_name=None):
        """Run ``fn`` now; device-side it is async.  Bumps write-var versions."""
        for v in read_vars:
            v.rethrow()
        audit = self._audit
        if audit is not None:
            audit.before_push(read_vars, write_vars, op_name)
        self.stats.ops_pushed += 1
        _flight.record("engine.push", op=op_name or "op")
        t0 = time.perf_counter() if self._hooks else 0.0
        try:
            # chaos hook: an injected op failure takes the same
            # set_exception path a real one would (tests assert the
            # async rethrow at the next read of a poisoned var)
            _inject("engine_push", op=op_name)
            out = fn()
        except Exception as e:
            # black box first: the poisoned vars will rethrow far from
            # here, so the ring must already hold the story
            _flight.record("engine.poison", op=op_name or "op",
                           error=type(e).__name__, writes=len(write_vars))
            _memdump.maybe_oom_report(e)
            for v in write_vars:
                v.set_exception(e)
            if audit is not None:
                audit.after_push(read_vars, write_vars, op_name)
            _flight.crash_dump("poison")
            raise
        for v in write_vars:
            v.on_write()
        if audit is not None:
            audit.after_push(read_vars, write_vars, op_name)
        if self._hooks:
            t1 = time.perf_counter()
            for h in self._hooks:
                h(op_name or getattr(fn, "__name__", "op"), t0, t1)
        return out

    def track_many(self, vals):
        """Track a batch of buffers (segment flush) in one extend."""
        self._inflight.extend(vals)
        if len(self._inflight) > self._inflight_cap:
            self._retire_inflight()

    def track(self, data):
        """Remember a dispatched buffer so wait_for_all() can sync on it."""
        self._inflight.append(data)
        if len(self._inflight) > self._inflight_cap:
            self._retire_inflight()

    def _retire_inflight(self):
        # ring full: retire the oldest half before dropping it, so
        # waitall() semantics stay exact (Engine::WaitForAll blocks on
        # every outstanding op; silently forgetting buffers could let
        # waitall() return with work — and async errors — in flight).
        # Only buffers still in flight cost a block; anything PJRT has
        # already finished (is_ready) is dropped without stalling.
        for _ in range(self._inflight_cap // 2):
            if not self._inflight:
                break
            d = self._inflight.popleft()
            try:
                ready = d.is_ready()
            except AttributeError:
                ready = False  # unknown state: assume still in flight
            except RuntimeError:
                continue  # donated-and-deleted buffer: nothing to wait on
            if not ready:
                try:
                    d.block_until_ready()  # mxlint: allow-host-sync
                except (AttributeError, RuntimeError):
                    pass

    # -- bulking ----------------------------------------------------------
    def _bulk_state(self):
        tls = self._bulk_tls
        if not hasattr(tls, "seg"):
            tls.seg = None     # this thread's open BulkSegment
            tls.scopes = []    # explicit bulk(size) scope stack
        return tls

    def bulk_size(self):
        """Segment cap for the next deferred op; 0 = dispatch eagerly.

        An explicit ``bulk(size)`` scope wins; otherwise ``BulkEngine``
        bulks up to ``MXNET_EXEC_BULK_EXEC_MAX_NODE`` when the mode knob
        (TRAIN/INFERENCE) allows.  Recording does NOT disable bulking:
        taped ops defer too, and the tape re-linearizes through the
        segment's promised values at backward time.  Implicit bulking
        still steps aside while an op profiler hook is attached (per-op
        spans, reference parity — unless MXNET_PROFILE_BULK=1 keeps
        segments fused under the profiler) and under the EA4xx auditor
        (it validates the eager push stream).
        """
        global _autograd
        st = self._bulk_state()
        if st.scopes:
            size = st.scopes[-1]
            return size if size > 0 else 0
        if self.kind != "BulkEngine":
            return 0
        if self._audit is not None or (self._hooks and not self._profile_bulk):
            return 0
        size = self._bulk_max
        if size <= 0:
            return 0
        if _autograd is None:
            from . import autograd as _autograd  # noqa: F811 (bind once)
        knob = self._bulk_train if _autograd.is_training() \
            else self._bulk_infer
        return size if knob else 0

    def current_segment(self, size=None):
        """This thread's open segment, creating one if needed."""
        st = self._bulk_state()
        seg = st.seg
        if seg is None or seg.flushed:
            seg = BulkSegment(self, size if size is not None
                              else self.bulk_size())
            st.seg = seg
        return seg

    def flush_bulk(self, origin="flush"):
        """Flush this thread's open segment, if any (cheap when none)."""
        st = self._bulk_state()
        seg = st.seg
        st.seg = None
        if seg is not None and not seg.flushed:
            seg.flush(origin)

    def flush_if_referencing(self, buffers, origin="donation_guard"):
        """Flush this thread's open segment if it captured any of
        ``buffers`` as an external input.

        Callers that donate buffers to XLA outside the bulk machinery
        (``gluon.Trainer``'s fused optimizer update) must drain pending
        deferred work first: XLA deletes a donated buffer even while a
        pending segment still holds it as an ext input, and the
        segment's later flush would read a dead array.  Cheap when the
        segment doesn't touch the buffers — bulking continues across
        the donating call.
        """
        st = self._bulk_state()
        seg = st.seg
        if seg is None or seg.flushed or not seg.ext:
            return
        if {id(b) for b in buffers} & seg._ext_ids.keys():
            self.flush_bulk(origin)

    def pending_reads(self, buffers):
        """Which of ``buffers`` this thread's open segment still reads.

        The page-liveness query behind ``serve.PagedKVArena``: a KV page
        buffer that appears as an ext input of an unflushed segment must
        not be overwritten or donated until that segment runs, so the
        arena asks here before recycling pages and flushes (via
        ``flush_if_referencing``) when the answer is non-empty.  Returns
        the subset of ``buffers`` captured as ext inputs — empty tuple
        when nothing pends, which is the cheap common case.
        """
        st = self._bulk_state()
        seg = st.seg
        if seg is None or seg.flushed or not seg.ext:
            return ()
        ids = seg._ext_ids
        return tuple(b for b in buffers if id(b) in ids)

    # -- sync -------------------------------------------------------------
    def wait_for_var(self, var):
        var.rethrow()

    def wait_for_all(self):
        self.flush_bulk("waitall")
        self.notify_sync("waitall")
        pending, self._inflight = self._inflight, collections.deque()
        for d in pending:
            try:
                d.block_until_ready()  # mxlint: allow-host-sync
            except (AttributeError, RuntimeError):
                # RuntimeError: buffer was donated to a segment and
                # deleted — by definition nothing can still be computing it
                pass

    # -- instrumentation --------------------------------------------------
    def add_hook(self, fn, kind="op"):
        """Register an instrumentation hook, idempotently.

        ``kind='op'``: ``fn(op_name, t_start, t_end)`` after every push.
        ``kind='sync'``: ``fn(origin)`` on every device->host sync
        (``asnumpy``/``wait_to_read``/``waitall`` report through
        ``notify_sync``) — the surface ``analysis.SyncCounter`` builds on.
        Registering the same hook twice is a no-op, so callers wrapped in
        retry/setup code can't double-count.
        """
        hooks = self._hooks_of(kind)
        if fn not in hooks:
            hooks.append(fn)

    def remove_hook(self, fn, kind="op"):
        hooks = self._hooks_of(kind)
        if fn in hooks:
            hooks.remove(fn)

    def _hooks_of(self, kind):
        if kind == "op":
            return self._hooks
        if kind == "sync":
            return self._sync_hooks
        raise ValueError("unknown hook kind %r (want 'op' or 'sync')" % kind)

    def notify_sync(self, origin):
        """Report one device->host sync to the sync hooks (cheap when none
        are registered — a single truthiness check on the hot path)."""
        _flight.record("engine.sync", origin=origin)
        if _metrics.enabled():
            so = self.stats.sync_origins
            so[origin] = so.get(origin, 0) + 1
        if self._sync_hooks:
            for h in self._sync_hooks:
                h(origin)


def _telemetry_collector():
    """Export engine aggregates at snapshot time (docs/observability.md).

    ``Engine.stats`` and the segment cache already count on the hot
    path; mirroring them here instead of inc'ing registry counters per
    push keeps telemetry's per-op cost at zero for these families.
    """
    eng = Engine._instance
    if eng is None:
        return
    st = eng.stats
    _metrics.counter("mxnet_engine_ops_pushed_total",
                     help="ops dispatched through Engine.push"
                     ).set(st.ops_pushed)
    _metrics.counter("mxnet_engine_bulk_ops_total",
                     help="ops that executed inside a bulk segment"
                     ).set(st.bulk_ops)
    _metrics.gauge("mxnet_engine_inflight_depth",
                   help="buffers tracked for waitall"
                   ).set(len(eng._inflight))
    for origin, n in list(st.sync_origins.items()):
        _metrics.counter("mxnet_engine_sync_total",
                         help="device->host syncs by origin",
                         origin=origin).set(n)
    for origin, n in list(st.flush_origins.items()):
        _metrics.counter("mxnet_engine_bulk_segments_total",
                         help="bulk segments flushed, by flush origin",
                         origin=origin).set(n)
    _metrics.counter("mxnet_engine_segment_cache_hits_total",
                     help="bulk segment executable cache hits"
                     ).set(_seg_cache_stats["hits"])
    _metrics.counter("mxnet_engine_segment_cache_disk_hits_total",
                     "bulk segments whose executable loaded from the "
                     "persistent compile cache (warm start, not a retrace)"
                     ).set(_seg_cache_stats["disk_hits"])
    _metrics.counter("mxnet_engine_segment_cache_misses_total",
                     help="bulk segment executable cache misses"
                     ).set(_seg_cache_stats["misses"])
    _metrics.counter("mxnet_engine_bulk_donated_total",
                     help="dead segment inputs donated to XLA"
                     ).set(st.bulk_donated)
    for label, tstats, tier in zip(_SEG_TIER_LABELS, _seg_tier_stats,
                                   _SEG_TIERS):
        _metrics.counter("mxnet_engine_segment_cache_tier_hits_total",
                         help="segment cache hits by size tier",
                         tier=label).set(tstats["hits"])
        _metrics.counter("mxnet_engine_segment_cache_tier_misses_total",
                         help="segment cache misses by size tier",
                         tier=label).set(tstats["misses"])
        _metrics.counter("mxnet_engine_segment_cache_tier_evictions_total",
                         help="segment cache LRU evictions by size tier",
                         tier=label).set(tstats["evictions"])
        _metrics.gauge("mxnet_engine_segment_cache_tier_size",
                       help="segment executables held by size tier",
                       tier=label).set(len(tier))


_metrics.register_collector(_telemetry_collector)


def waitall():
    Engine.get().wait_for_all()


def set_bulk_size(size):
    """Set the default segment cap (parity: mxnet.engine.set_bulk_size).
    Returns the previous cap.  Only takes effect under ``BulkEngine`` or
    inside an explicit :class:`bulk` scope."""
    eng = Engine.get()
    size = int(size)
    if size <= 0:
        # disabling bulking must fully disable deferral, not just cap new
        # segments: any already-deferred ops flush NOW so everything after
        # this call observes concrete program order
        eng.flush_bulk("bulk_size_zero")
    prev, eng._bulk_max = eng._bulk_max, size
    return prev


class bulk:
    """Scope bulking consecutive imperative ops (parity: mxnet.engine.bulk).

    ::

        with mx.engine.bulk(16):
            for _ in range(100):
                x = x + 1          # deferred; flushes every 16 ops
        x.asnumpy()                # sync point: flushes the tail

    Works under any engine kind — the scope overrides the engine default,
    so ``bulk(0)`` also force-disables bulking under ``BulkEngine``.
    Entering and leaving the scope are segment boundaries.
    """

    def __init__(self, size):
        self.size = int(size)

    def __enter__(self):
        eng = Engine.get()
        eng.flush_bulk("bulk_scope_enter")
        eng._bulk_state().scopes.append(self.size)
        return self

    def __exit__(self, *exc):
        eng = Engine.get()
        try:
            eng.flush_bulk("bulk_scope_exit")
        finally:
            eng._bulk_state().scopes.pop()
