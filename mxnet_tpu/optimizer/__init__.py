"""Optimizer package (parity: python/mxnet/optimizer/)."""
from .optimizer import (  # noqa: F401
    Optimizer, SGD, NAG, SGLD, Adam, AdamW, AdaGrad, AdaDelta, RMSProp, Ftrl,
    Signum, FTML, DCASGD, Nadam, LAMB, LARS, Test, Updater, get_updater,
    create, register,
)
