"""Optimizers.

Reference: ``python/mxnet/optimizer/optimizer.py`` — ``Optimizer`` base with a
string registry (``:53,145``), ~20 implementations delegating to fused C++
update ops (``src/operator/optimizer_op.cc``), plus ``Updater`` for the
KVStore server-side path.

TPU-native: every optimizer defines one pure function
``_step(weight, grad, state, lr, wd, t)`` in jax.  The imperative
``update()`` API jits it per-optimizer (XLA caches per shape), and the gluon
``Trainer`` goes further: it jits ONE update over the *entire* parameter list
with donated buffers — the analogue of the reference's multi-tensor fused ops
(``multi_sgd_update``, ``src/operator/contrib/multi_lamb.cc``) but covering
every optimizer automatically.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray


class Optimizer:
    """Base optimizer (parity: optimizer.Optimizer)."""

    opt_registry = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            # reference parity: only base_lr is adopted — Poly/Cosine
            # deliberately keep their construction-time anchor
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = dict(param_idx2name)
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}
        self._jit_cache = {}

    # -- registry ---------------------------------------------------------
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() not in Optimizer.opt_registry:
            raise MXNetError("cannot find optimizer %s" % name)
        return Optimizer.opt_registry[name.lower()](**kwargs)

    # -- lr / wd bookkeeping ----------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError(
                "LRScheduler of the optimizer has already been defined")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) \
            if self.lr_scheduler is not None else self.lr
        param = self.param_dict.get(index)
        if param is not None:
            lr *= param.lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        param = self.param_dict.get(index)
        if param is not None:
            wd *= param.wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- state ------------------------------------------------------------
    def create_state(self, index, weight):
        """Per-parameter state pytree (jax arrays)."""
        return None

    def create_state_multi_precision(self, index, weight):
        return self.create_state(index, weight)

    # -- the pure update --------------------------------------------------
    def _step(self, weight, grad, state, lr, wd, t):
        """Pure: (w, g, s, lr, wd, t) -> (new_w, new_s).  Override."""
        raise NotImplementedError

    def _clip_rescale(self, grad):
        g = grad * self.rescale_grad
        if self.clip_gradient is not None and self.clip_gradient >= 0:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def _get_jit_step(self):
        # _step closes over rescale_grad/clip_gradient as trace-time
        # constants, so the jitted callable must be keyed on their values —
        # Trainer.step mutates rescale_grad every call.
        key = (self.rescale_grad, self.clip_gradient)
        fn = self._jit_cache.get(key)
        if fn is None:
            rescale, clip = key
            opt = self

            # A fresh function object per key: jitting the same bound method
            # twice hits jax's shared trace cache and would resurrect the
            # old baked-in constants.
            def _step_with_consts(weight, grad, state, lr, wd, t):
                saved = (opt.rescale_grad, opt.clip_gradient)
                opt.rescale_grad, opt.clip_gradient = rescale, clip
                try:
                    new_w, new_s = opt._step(weight, grad, state, lr, wd, t)
                finally:
                    opt.rescale_grad, opt.clip_gradient = saved
                # keep weight/state dtypes stable under f32 lr/wd scalars
                # (bf16 params would otherwise be silently promoted)
                new_w = new_w.astype(weight.dtype)
                new_s = jax.tree_util.tree_map(
                    lambda a, b: a.astype(b.dtype), new_s, state)
                return new_w, new_s

            fn = jax.jit(_step_with_consts)
            self._jit_cache[key] = fn
        return fn

    def _get_sparse_jit_step(self):
        """Lazy (row-sparse) update executable: only rows present in the
        gradient are touched — weight AND optimizer state (reference
        lazy_update semantics, optimizer_op.cc sparse SGD/Adam variants).

        Generic over any optimizer whose ``_step`` is row-wise elementwise:
        gather the touched rows of weight/state, run the dense ``_step`` on
        the slice, scatter back.
        """
        if not hasattr(self, "_sparse_jit_cache"):
            self._sparse_jit_cache = {}
        key = (self.rescale_grad, self.clip_gradient)
        fn = self._sparse_jit_cache.get(key)
        if fn is None:
            rescale, clip = key
            opt = self

            def run(w, st, g, i, lr_, wd_, t_):
                saved = (opt.rescale_grad, opt.clip_gradient)
                opt.rescale_grad, opt.clip_gradient = rescale, clip
                try:
                    w_rows = w[i]
                    st_rows = jax.tree_util.tree_map(lambda s: s[i], st)
                    nw, nst = opt._step(w_rows, g, st_rows, lr_, wd_, t_)
                finally:
                    opt.rescale_grad, opt.clip_gradient = saved
                w_new = w.at[i].set(nw.astype(w.dtype))
                st_new = jax.tree_util.tree_map(
                    lambda s, ns: s.at[i].set(ns.astype(s.dtype)),
                    st, nst)
                return w_new, st_new

            fn = jax.jit(run)
            self._sparse_jit_cache[key] = fn
        return fn

    # -- imperative API (parity: Optimizer.update) -------------------------
    def update(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(index, (list, tuple)):
            for i, w, g, s in zip(index, weight, grad, state):
                self.update(i, w, g, s)
            return
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        if isinstance(grad, RowSparseNDArray):
            w = weight.data() if isinstance(weight, NDArray) else weight
            rsp = grad.compact()
            idx = rsp.indices.data().astype(jnp.int32)
            vals = rsp.values.data().astype(w.dtype)
            if hasattr(w, "devices"):
                dev = list(w.devices())[0]
                idx = jax.device_put(idx, dev)
                vals = jax.device_put(vals, dev)
            new_w, new_s = self._get_sparse_jit_step()(
                w, state, vals, idx,
                jnp.float32(lr), jnp.float32(wd), jnp.int32(t))
            if isinstance(weight, NDArray):
                weight._set_data(new_w)
            return new_w, new_s
        w = weight.data() if isinstance(weight, NDArray) else weight
        g = grad.data() if isinstance(grad, NDArray) else grad
        new_w, new_s = self._get_jit_step()(
            w, g, state, jnp.float32(lr), jnp.float32(wd), jnp.int32(t))
        if isinstance(weight, NDArray):
            weight._set_data(new_w)
        return new_w, new_s

    def update_multi_precision(self, index, weight, grad, state):
        return self.update(index, weight, grad, state)

    def __repr__(self):
        return "%s(lr=%s, wd=%s)" % (
            type(self).__name__, self.learning_rate, self.wd)


register = Optimizer.register


def create(name, **kwargs):
    return Optimizer.create_optimizer(name, **kwargs)


# ---------------------------------------------------------------------------
# concrete optimizers
# ---------------------------------------------------------------------------
@register
class SGD(Optimizer):
    """SGD with momentum (parity: optimizer.SGD; op sgd_mom_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        w = weight.data() if isinstance(weight, NDArray) else weight
        return jnp.zeros(w.shape, w.dtype)

    def _step(self, weight, grad, state, lr, wd, t):
        g = self._clip_rescale(grad) + wd * weight
        if self.momentum == 0.0 or state is None:
            return weight - lr * g, state
        mom = self.momentum * state - lr * g
        return weight + mom, mom


@register
class NAG(Optimizer):
    """Nesterov SGD (parity: optimizer.NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        w = weight.data() if isinstance(weight, NDArray) else weight
        return jnp.zeros(w.shape, w.dtype)

    def _step(self, weight, grad, state, lr, wd, t):
        g = self._clip_rescale(grad) + wd * weight
        if self.momentum == 0.0 or state is None:
            return weight - lr * g, state
        mom = self.momentum * state + g
        return weight - lr * (g + self.momentum * mom), mom


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (parity: optimizer.SGLD).

    The per-parameter PRNG key lives in the optimizer state so noise is
    independent across parameters and reseedable via ``mx.random.seed``.
    """

    def create_state(self, index, weight):
        from .. import random as _random

        return _random.next_key()

    def _step(self, weight, grad, state, lr, wd, t):
        g = self._clip_rescale(grad) + wd * weight
        new_key, sub = jax.random.split(state)
        noise = jax.random.normal(sub, weight.shape, jnp.float32) \
            * jnp.sqrt(lr)
        return weight - 0.5 * lr * g + noise.astype(weight.dtype), new_key


@register
class Adamax(Optimizer):
    """AdaMax — Adam on the infinity norm (parity: optimizer.Adamax,
    Kingma & Ba section 7)::

        m = beta1*m + (1-beta1)*g
        u = max(beta2*u, |g|)
        w -= lr/(1-beta1^t) * m/u
    """

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return (jnp.zeros(w.shape, w.dtype), jnp.zeros(w.shape, w.dtype))

    def _step(self, weight, grad, state, lr, wd, t):
        mean, u = state
        t = t.astype(jnp.float32)
        g = self._clip_rescale(grad) + wd * weight
        mean = self.beta1 * mean + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        lr_t = lr / (1.0 - self.beta1 ** t)
        new_w = weight - lr_t * mean / jnp.maximum(u, 1e-30)
        return new_w, (mean, u)


@register
class LBSGD(Optimizer):
    """Large-Batch SGD: momentum SGD with a warmup learning-rate
    multiplier and LARS layer-wise scaling (parity: optimizer.LBSGD).

    The warmup multiplier ramps 1 → ``batch_scale`` over
    ``warmup_epochs * updates_per_epoch`` updates with the chosen
    ``warmup_strategy`` (``linear``/``power2``/``sqrt``); strategy
    ``lars`` instead scales each layer's rate by
    ``sqrt(||w||² / (||g||² + wd·||w||² + eps))`` clipped to
    [0.01, 100] (the reference's ``_get_lars``).  Deviation (documented):
    the reference can also EMULATE a large batch by cumulating
    ``batch_scale`` micro-batch gradients host-side; here the TPU-native
    route to a large batch is the sharded data-parallel train step, so
    every update is treated as one macro-batch step.
    """

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60,
                 **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = float(batch_scale)
        self.updates_per_epoch = updates_per_epoch

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        w = weight.data() if isinstance(weight, NDArray) else weight
        return jnp.zeros(w.shape, w.dtype)

    def _warmup_mult(self, t):
        nwup = float(self.warmup_epochs * self.updates_per_epoch)
        maxmult = self.batch_scale
        if nwup <= 1 or maxmult <= 1 \
                or self.warmup_strategy not in ("linear", "power2", "sqrt"):
            return jnp.float32(1.0)
        frac = jnp.minimum(t.astype(jnp.float32) / nwup, 1.0)
        if self.warmup_strategy == "power2":
            frac = frac * frac
        elif self.warmup_strategy == "sqrt":
            frac = jnp.sqrt(frac)
        return 1.0 + (maxmult - 1.0) * frac

    def _step(self, weight, grad, state, lr, wd, t):
        g = self._clip_rescale(grad) + wd * weight
        if self.warmup_strategy == "lars":
            w2 = jnp.sum(jnp.square(weight).astype(jnp.float32))
            g2 = jnp.sum(jnp.square(g).astype(jnp.float32))
            lars = jnp.sqrt(w2 / (g2 + wd * w2 + 1e-18))
            lr = lr * jnp.clip(lars, 0.01, 100.0)
        else:
            lr = lr * self._warmup_mult(t)
        if self.momentum == 0.0 or state is None:
            return weight - lr * g, state
        mom = self.momentum * state - lr * g
        return weight + mom, mom


@register
class Adam(Optimizer):
    """Adam with bias correction (parity: optimizer.Adam; op adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return (jnp.zeros(w.shape, w.dtype), jnp.zeros(w.shape, w.dtype))

    def _step(self, weight, grad, state, lr, wd, t):
        mean, var = state
        t = t.astype(jnp.float32)
        lr_t = lr * jnp.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        g = self._clip_rescale(grad) + wd * weight
        mean = self.beta1 * mean + (1 - self.beta1) * g
        var = self.beta2 * var + (1 - self.beta2) * jnp.square(g)
        new_w = weight - lr_t * mean / (jnp.sqrt(var) + self.epsilon)
        return new_w, (mean, var)


@register
class AdamW(Optimizer):
    """Decoupled weight decay Adam (parity: contrib adamw_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return (jnp.zeros(w.shape, w.dtype), jnp.zeros(w.shape, w.dtype))

    def _step(self, weight, grad, state, lr, wd, t):
        mean, var = state
        t = t.astype(jnp.float32)
        lr_t = lr * jnp.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        g = self._clip_rescale(grad)
        mean = self.beta1 * mean + (1 - self.beta1) * g
        var = self.beta2 * var + (1 - self.beta2) * jnp.square(g)
        new_w = weight - lr_t * mean / (jnp.sqrt(var) + self.epsilon) \
            - lr * wd * weight
        return new_w, (mean, var)


@register
class AdaGrad(Optimizer):
    """Parity: optimizer.AdaGrad."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return jnp.zeros(w.shape, w.dtype)

    def _step(self, weight, grad, state, lr, wd, t):
        g = self._clip_rescale(grad) + wd * weight
        hist = state + jnp.square(g)
        return weight - lr * g / jnp.sqrt(hist + self.float_stable_eps), hist


@register
class AdaDelta(Optimizer):
    """Parity: optimizer.AdaDelta."""

    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return (jnp.zeros(w.shape, w.dtype), jnp.zeros(w.shape, w.dtype))

    def _step(self, weight, grad, state, lr, wd, t):
        acc_g, acc_delta = state
        g = self._clip_rescale(grad) + wd * weight
        acc_g = self.rho * acc_g + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_delta + self.epsilon) / \
            jnp.sqrt(acc_g + self.epsilon) * g
        acc_delta = self.rho * acc_delta + (1 - self.rho) * jnp.square(delta)
        return weight - delta, (acc_g, acc_delta)


@register
class RMSProp(Optimizer):
    """Parity: optimizer.RMSProp (centered=True → Alex Graves variant)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        z = jnp.zeros(w.shape, w.dtype)
        if self.centered:
            return (z, z, z)  # n, g_mean, delta
        return (z,)

    def _step(self, weight, grad, state, lr, wd, t):
        g = self._clip_rescale(grad) + wd * weight
        if self.centered:
            n, g_mean, delta = state
            n = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * n
            g_mean = (1 - self.gamma1) * g + self.gamma1 * g_mean
            delta = self.gamma2 * delta - lr * g / jnp.sqrt(
                n - jnp.square(g_mean) + self.epsilon)
            w = weight + delta
            state = (n, g_mean, delta)
        else:
            (n,) = state
            n = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * n
            w = weight - lr * g / (jnp.sqrt(n) + self.epsilon)
            state = (n,)
        if self.clip_weights:
            w = jnp.clip(w, -self.clip_weights, self.clip_weights)
        return w, state


@register
class Ftrl(Optimizer):
    """Parity: optimizer.Ftrl (op ftrl_update)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return (jnp.zeros(w.shape, w.dtype), jnp.zeros(w.shape, w.dtype))

    def _step(self, weight, grad, state, lr, wd, t):
        z, n = state
        g = self._clip_rescale(grad)
        sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr
        z = z + g - sigma * weight
        n = n + jnp.square(g)
        new_w = jnp.where(
            jnp.abs(z) > self.lamda1,
            -(z - jnp.sign(z) * self.lamda1) /
            ((self.beta + jnp.sqrt(n)) / lr + wd),
            jnp.zeros_like(weight))
        return new_w, (z, n)


@register
class Signum(Optimizer):
    """Parity: optimizer.Signum (signSGD with momentum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        w = weight.data() if isinstance(weight, NDArray) else weight
        return jnp.zeros(w.shape, w.dtype)

    def _step(self, weight, grad, state, lr, wd, t):
        g = self._clip_rescale(grad)
        if self.momentum == 0.0 or state is None:
            step = jnp.sign(g + wd * weight)
            return weight * (1 - lr * self.wd_lh) - lr * step, state
        mom = self.momentum * state - (1 - self.momentum) * (g + wd * weight)
        return weight * (1 - lr * self.wd_lh) + lr * jnp.sign(mom), mom


@register
class FTML(Optimizer):
    """Parity: optimizer.FTML."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        z = jnp.zeros(w.shape, w.dtype)
        return (z, z, z)  # d, v, z

    def _step(self, weight, grad, state, lr, wd, t):
        d, v, z = state
        t = t.astype(jnp.float32)
        g = self._clip_rescale(grad) + wd * weight
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        d_t = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(v / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * d
        z = self.beta1 * z + (1 - self.beta1) * g - sigma * weight
        return -z / d_t, (d_t, v, z)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (parity: optimizer.DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return (jnp.zeros(w.shape, w.dtype), w)  # mom, previous_weight

    def _step(self, weight, grad, state, lr, wd, t):
        mom, prev = state
        g = self._clip_rescale(grad) + wd * weight
        comp = g + self.lamda * g * g * (weight - prev)
        mom = self.momentum * mom - lr * comp
        return weight + mom, (mom, weight + mom)


@register
class Nadam(Optimizer):
    """Parity: optimizer.Nadam."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return (jnp.zeros(w.shape, w.dtype), jnp.zeros(w.shape, w.dtype),
                jnp.ones((), jnp.float32))  # m, v, m_schedule

    def _step(self, weight, grad, state, lr, wd, t):
        m, v, m_sched = state
        t = t.astype(jnp.float32)
        g = self._clip_rescale(grad) + wd * weight
        mu_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mu_tp1 = self.beta1 * (1 - 0.5 * 0.96 **
                               ((t + 1) * self.schedule_decay))
        m_sched_t = m_sched * mu_t
        m_sched_tp1 = m_sched_t * mu_tp1
        g_prime = g / (1 - m_sched_t)
        m = self.beta1 * m + (1 - self.beta1) * g
        m_prime = m / (1 - m_sched_tp1)
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        v_prime = v / (1 - self.beta2 ** t)
        m_bar = (1 - mu_t) * g_prime + mu_tp1 * m_prime
        new_w = weight - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon)
        return new_w, (m, v, m_sched_t)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments (parity: optimizer.LAMB / multi_lamb.cc)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return (jnp.zeros(w.shape, w.dtype), jnp.zeros(w.shape, w.dtype))

    def _step(self, weight, grad, state, lr, wd, t):
        mean, var = state
        t = t.astype(jnp.float32)
        g = self._clip_rescale(grad)
        mean = self.beta1 * mean + (1 - self.beta1) * g
        var = self.beta2 * var + (1 - self.beta2) * jnp.square(g)
        if self.bias_correction:
            mean_hat = mean / (1 - self.beta1 ** t)
            var_hat = var / (1 - self.beta2 ** t)
        else:
            mean_hat, var_hat = mean, var
        update = mean_hat / (jnp.sqrt(var_hat) + self.epsilon) + wd * weight
        w_norm = jnp.linalg.norm(weight.astype(jnp.float32))
        u_norm = jnp.linalg.norm(update.astype(jnp.float32))
        ratio = jnp.where(
            (w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        if self.lower_bound is not None:
            ratio = jnp.maximum(ratio, self.lower_bound)
        if self.upper_bound is not None:
            ratio = jnp.minimum(ratio, self.upper_bound)
        return weight - lr * ratio * update, (mean, var)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (parity: multi_lars.cc)."""

    def __init__(self, momentum=0.9, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return jnp.zeros(w.shape, w.dtype)

    def _step(self, weight, grad, state, lr, wd, t):
        g = self._clip_rescale(grad)
        w_norm = jnp.linalg.norm(weight.astype(jnp.float32))
        g_norm = jnp.linalg.norm(g.astype(jnp.float32))
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon), 1.0)
        g = g + wd * weight
        mom = self.momentum * state + lr * trust * g
        return weight - mom, mom


@register
class Test(Optimizer):
    """Trivial optimizer used by unit tests (parity: optimizer.Test)."""

    def create_state(self, index, weight):
        w = weight.data() if isinstance(weight, NDArray) else weight
        return jnp.zeros(w.shape, w.dtype)

    def _step(self, weight, grad, state, lr, wd, t):
        return weight + grad * self.rescale_grad, state


# ---------------------------------------------------------------------------
# Updater (parity: optimizer.Updater / get_updater) — the KVStore server path
# ---------------------------------------------------------------------------
class Updater:
    """Applies an optimizer keyed by integer index (server-side semantics)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        new_w, new_s = self.optimizer.update(
            index, weight, grad, self.states[index])
        self.states[index] = new_s

    def get_states(self, dump_optimizer=False):
        import pickle

        return pickle.dumps(
            {k: jax.device_get(v) for k, v in self.states.items()}
            if not dump_optimizer else
            (self.states, self.optimizer))

    def set_states(self, states):
        import pickle

        loaded = pickle.loads(states)
        if isinstance(loaded, tuple):
            self.states, self.optimizer = loaded
        else:
            self.states = loaded


def get_updater(optimizer):
    return Updater(optimizer)
