"""Device contexts, with a first-class ``mx.tpu()``.

Reference: ``python/mxnet/context.py`` (``mx.cpu()/mx.gpu()``).  The rebuild's
north star is a framework where TPU is the default accelerator: a ``Context``
names a logical device and resolves to a concrete ``jax.Device``.  All array
placement goes through ``Context.jax_device`` + ``jax.device_put``; compiled
executables are placed by XLA.

Unlike the reference (device_typeid enum routed through the C ABI), a context
here is a thin value object; there is no per-device stream state to manage —
PJRT owns streams.
"""
from __future__ import annotations

import threading

import jax

_DEVTYPE2ID = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
_ID2DEVTYPE = {v: k for k, v in _DEVTYPE2ID.items()}


def _accelerator_platforms():
    return ("tpu", "axon", "cuda", "rocm", "gpu")


class Context:
    """A logical device. ``Context('tpu', 0)`` resolves to the first TPU chip.

    With ``mesh=`` a context names a device *set*: ``mx.tpu(mesh=...)``
    entered as a scope also sets the ambient mesh, so ``nd.shard`` /
    ``JitTrainStep`` inside the scope pick it up implicitly (the GSPMD
    substrate, ``mxnet_tpu/sharding/``).  Placement of plain arrays
    still resolves to one device (``jax_device``); the mesh governs
    sharded placement.
    """

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0, mesh=None):
        if isinstance(device_type, Context):
            if mesh is None:
                mesh = device_type.mesh
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in _DEVTYPE2ID:
            raise ValueError("unknown device type %r" % (device_type,))
        self.device_type = device_type
        self.device_id = device_id
        if mesh is not None:
            from .sharding import Mesh as _Mesh

            mesh = mesh if isinstance(mesh, _Mesh) else _Mesh(mesh)
        self.mesh = mesh
        self._old_ctx = None

    @property
    def device_typeid(self):
        return _DEVTYPE2ID[self.device_type]

    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device (cached per process device list)."""
        return _resolve_jax_device(self.device_type, self.device_id)

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
            and self.mesh == other.mesh
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id, self.mesh))

    def __repr__(self):
        if self.mesh is not None:
            return "%s(%d, mesh=%s)" % (self.device_type, self.device_id,
                                        dict(self.mesh.shape))
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        if self.mesh is not None:
            from . import sharding as _sharding

            _sharding.push_mesh(self.mesh)
        return self

    def __exit__(self, *args):
        Context._default_ctx.value = self._old_ctx
        if self.mesh is not None:
            from . import sharding as _sharding

            _sharding.pop_mesh()

    def empty_cache(self):
        """Parity with mx.Context.empty_cache; PJRT pools its own memory."""
        try:
            for buf in self.jax_device.live_buffers():  # pragma: no cover
                del buf
        except Exception:
            pass


def _resolve_jax_device(device_type, device_id):
    # local_devices, not devices: in a multi-process (multi-host) runtime
    # jax.devices() is the GLOBAL list and entry 0 may belong to another
    # process — a Context always names a device THIS process can address
    devices = jax.local_devices()
    if device_type in ("cpu", "cpu_pinned", "cpu_shared"):
        try:
            cpus = jax.local_devices(backend="cpu")
        except RuntimeError:
            cpus = [d for d in devices if d.platform == "cpu"]
        if cpus:
            return cpus[device_id % len(cpus)]
        return devices[0]
    # tpu/gpu: any accelerator platform; tolerate experimental platform names
    accels = [d for d in devices if d.platform in _accelerator_platforms()]
    if not accels:
        accels = [d for d in devices if d.platform != "cpu"]
    if not accels:
        # No accelerator present (e.g. CPU-only test run): fall back silently so
        # mx.tpu() code paths stay testable on the 8-device virtual CPU mesh.
        accels = devices
    return accels[device_id % len(accels)]


def cpu(device_id=0, mesh=None):
    return Context("cpu", device_id, mesh=mesh)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0, mesh=None):
    """Kept for API parity; resolves to an accelerator (TPU on TPU hosts)."""
    return Context("gpu", device_id, mesh=mesh)


def tpu(device_id=0, mesh=None):
    """First-class TPU context (north-star feature; no reference counterpart).

    ``mx.tpu(mesh={"data": 8})`` names a device set: entering it as a
    scope makes the mesh ambient for sharded placement."""
    return Context("tpu", device_id, mesh=mesh)


def num_gpus():
    return len([d for d in jax.devices() if d.platform in ("cuda", "rocm", "gpu")])


def num_tpus():
    return len([d for d in jax.devices() if d.platform in ("tpu", "axon")])


def default_context():
    """The ambient context: TPU if present, else CPU (reference defaults to cpu)."""
    if getattr(Context._default_ctx, "value", None) is not None:
        return Context._default_ctx.value
    return Context("cpu", 0)


def current_context():
    return default_context()


def _best_context():
    """TPU when available — used by tests/benchmarks, not as the silent default."""
    if num_tpus() > 0:
        return tpu(0)
    return cpu(0)
