"""Parity re-exports for ``mx.executor_manager`` (reference
``python/mxnet/executor_manager.py``).

The reference's DataParallelExecutorManager machinery lives here; in this
rebuild the same roles are implemented by the Module API's executor group
(`module/executor_group.py`) — batch slicing across contexts, forward/
backward fan-out, gradient accumulation — so this module re-exports them
under the reference import path.
"""
from .module.executor_group import DataParallelExecutorGroup  # noqa: F401


def _split_input_slice(batch_size, work_load_list):
    """Slice [0, batch_size) proportionally to work_load_list (reference
    executor_manager.py:35)."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        end = batch_size if i == len(work_load_list) - 1 else \
            start + int(round(batch_size * w / total))
        if end <= start:
            raise ValueError("Too many slices. Some splits are empty.")
        slices.append(slice(start, end))
        start = end
    return slices
