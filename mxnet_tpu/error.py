"""Structured error classes.

Parity: ``python/mxnet/error.py`` — the reference maps C-ABI error
prefixes ("ValueError: ...") onto registered Python exception types via
``register_error``.  There is no C ABI here (errors are ordinary Python
exceptions end-to-end), so ``register`` keeps the registry purely for
API parity: code that registers custom error types and code that looks
them up by name keeps working, and the standard taxonomy
(``InternalError``, ``NotImplementedForTPU`` alias, builtin
ValueError/TypeError/AttributeError/IndexError) is pre-registered.
"""
from __future__ import annotations

from .base import MXNetError, NotSupportedForTPU

__all__ = ["MXNetError", "InternalError", "register"]

_ERROR_REGISTRY = {}


def register(name_or_cls, cls=None):
    """Register an error class under a name (parity:
    ``base.register_error``).  Usable as a decorator::

        @mx.error.register
        class MyError(mx.MXNetError): ...

    or with an explicit name: ``register("ValueError", ValueError)``.
    """
    if cls is not None:
        _ERROR_REGISTRY[str(name_or_cls)] = cls
        return cls
    _ERROR_REGISTRY[name_or_cls.__name__] = name_or_cls
    return name_or_cls


def get_error_class(name, default=MXNetError):
    """Look up a registered error class by name."""
    return _ERROR_REGISTRY.get(name, default)


@register
class InternalError(MXNetError):
    """Internal error in the runtime (parity: error.py:31).  The hint
    suffix mirrors the reference's convention of pointing users at the
    issue tracker for errors that indicate a framework bug."""

    def __init__(self, msg):
        if "hint:" not in msg:
            msg += ("\nhint: you hit an internal error; please report it "
                    "with the full traceback")
        super().__init__(msg)


register("MXNetError", MXNetError)
register("NotSupportedForTPU", NotSupportedForTPU)
register("ValueError", ValueError)
register("TypeError", TypeError)
register("AttributeError", AttributeError)
register("IndexError", IndexError)
