"""``mx.profiler`` — profiling API with chrome-trace export.

Capability parity with the reference profiler
(``python/mxnet/profiler.py:33-224`` API; ``src/profiler/profiler.h:251``
engine-hooked op stats; ``DumpProfile:299`` chrome://tracing JSON;
``aggregate_stats.cc`` summary tables; Domain/Task/Frame/Counter/Marker
primitives ``profiler.h:768-910``).

TPU-native mechanism: eager-mode op timings come from the engine's push
hook (each dispatched executable reports wall time); device-side detail
comes from the XLA/PJRT profiler — ``set_config(xla_trace_dir=...)``
arms ``jax.profiler`` so a ``run``→``stop`` window also captures an
xplane trace (viewable in TensorBoard/Perfetto, the TPU analogue of the
reference's NVTX/VTune emitters).  ``dump()`` writes standard
chrome://tracing JSON.
"""
from __future__ import annotations

import json
import threading
import time

_lock = threading.Lock()
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_imperative": True,
    "profile_symbolic": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    "continuous_dump": False,
    "xla_trace_dir": None,
}
_state = {"running": False, "paused": False, "hook": None,
          "xla_active": False}
_events = []  # chrome trace event dicts
_t0 = time.perf_counter()
# wall-clock time of local ts==0: lets telemetry.merge_traces align
# dumps from different processes (each has its own perf_counter epoch)
# onto one timeline.  Embedded in every dump as otherData.wall_t0_us.
_wall0 = time.time()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def _recording():
    return _state["running"] and not _state["paused"]


def set_config(**kwargs):
    """Configure the profiler (parity: profiler.py:33).

    Accepted keys: ``filename``, ``profile_all``, ``profile_symbolic``,
    ``profile_imperative``, ``profile_memory``, ``profile_api``,
    ``aggregate_stats``, ``continuous_dump`` and the TPU-specific
    ``xla_trace_dir`` (directory for the PJRT xplane trace).
    """
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise ValueError("invalid profiler options: %s" % sorted(unknown))
    _config.update(kwargs)


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Deprecated alias (parity: profiler.py:70)."""
    set_config(filename=filename)


def _engine_hook(op_name, t_start, t_end):
    # a flushed bulk segment arrives as ONE push named bulk_segment[N];
    # give it its own category (with the fused op count in args) so
    # traces distinguish fused segments from single-op dispatches and
    # tooling can sum ops without parsing names (engine.BulkSegment.flush)
    args = None
    if op_name.startswith("bulk_segment["):
        cat = "bulk"
        try:
            args = {"ops": int(op_name[len("bulk_segment["):-1])}
        except ValueError:
            pass
    else:
        cat = "operator"
    add_span(op_name, (t_start - _t0) * 1e6, (t_end - _t0) * 1e6, cat=cat,
             args=args)


def add_span(name, t_start_us, t_end_us, cat="operator", tid=None,
             pid=0, args=None):
    """Record one complete duration event; timestamps are ``_now_us()``
    values (server request handlers and other non-engine
    instrumentation report through this).  ``tid`` defaults to the
    calling thread so concurrent handlers land on distinct trace
    tracks instead of overlapping on one.  ``pid`` is the trace
    process track — dist servers record at ``rank + 1`` so merged
    traces keep worker/server timelines apart; ``args`` carries
    correlation ids (e.g. the kvstore wire span id)."""
    if not _recording():
        return
    if tid is None:
        import threading

        tid = threading.get_ident() & 0xFFFF
    ev = {
        "name": name, "ph": "X", "cat": cat,
        "ts": t_start_us, "dur": t_end_us - t_start_us,
        "pid": pid, "tid": tid,
    }
    if args:
        ev["args"] = dict(args)
    with _lock:
        _events.append(ev)


def set_state(state="stop", profile_process="worker"):
    """Start ('run') or stop ('stop') profiling (parity: profiler.py:89).

    ``profile_process='server'`` routes the command over the dist
    KVStore wire to every server (parity: the reference's
    kSetProfilerParams server command, include/mxnet/kvstore.h:49) —
    call ``set_kvstore_handle(kv)`` first.
    """
    from .engine import Engine

    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if profile_process == "server":
        _require_kv_handle().set_server_profiler_state(state)
        return
    eng = Engine.get()
    if state == "run" and not _state["running"]:
        _state["running"] = True
        _state["paused"] = False
        if _state["hook"] is None:
            _state["hook"] = _engine_hook
            eng.add_hook(_engine_hook)
        if _config["xla_trace_dir"]:
            try:
                import jax

                jax.profiler.start_trace(_config["xla_trace_dir"])
                _state["xla_active"] = True
            except Exception:  # device-side tracing is best-effort
                _state["xla_active"] = False
    elif state == "stop" and _state["running"]:
        _state["running"] = False
        if _state["hook"] is not None:
            eng.remove_hook(_state["hook"])
            _state["hook"] = None
        if _state["xla_active"]:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            _state["xla_active"] = False


def profiler_set_state(state="stop"):
    """Deprecated alias (parity: profiler.py:109)."""
    set_state(state)


def pause(profile_process="worker"):
    """Suspend event collection without tearing down (parity: :193).
    ``profile_process='server'`` pauses every dist server's profiler
    over the kvstore wire, same routing as ``set_state``/``dump``."""
    if profile_process == "server":
        _require_kv_handle().server_profiler_pause()
        return
    _state["paused"] = True


def resume(profile_process="worker"):
    if profile_process == "server":
        _require_kv_handle().server_profiler_resume()
        return
    _state["paused"] = False


def dump(finished=True, profile_process="worker"):
    """Write collected events as chrome://tracing JSON (parity: :122).
    ``profile_process='server'`` makes every dist server write ITS OWN
    trace file server-side (reference server profiling contract)."""
    if profile_process == "server":
        _require_kv_handle().server_profiler_dump(finished=finished)
        return
    if finished and _state["running"]:
        set_state("stop")
    trace = get_trace()
    with open(_config["filename"], "w") as f:
        json.dump(trace, f)
    if not _config["continuous_dump"]:
        with _lock:
            _events.clear()


def get_trace():
    """The collected events as a chrome-trace dict (what ``dump`` would
    write), without touching disk or profiler state.  Includes the
    wall-clock anchor ``telemetry.merge_traces`` aligns timelines by."""
    with _lock:
        return {"traceEvents": list(_events), "displayTimeUnit": "ms",
                "otherData": {"wall_t0_us": _wall0 * 1e6}}


def dump_profile():
    """Deprecated alias (parity: :143)."""
    dump(finished=False)


def dumps(reset=False, format="table", sort_by="total", ascending=False,
          aggregate=True):
    """Aggregate per-op summary (parity: :151, aggregate_stats.cc —
    count/total/avg/min/max per op name).  ``aggregate=False`` returns
    the raw event list as JSON instead of the table."""
    if not aggregate:
        with _lock:
            out = json.dumps(list(_events))
            if reset:
                _events.clear()
        return out
    with _lock:
        stats = {}
        for e in _events:
            if e["ph"] != "X":
                continue
            s = stats.setdefault(e["name"],
                                 {"count": 0, "total": 0.0,
                                  "min": float("inf"), "max": 0.0})
            s["count"] += 1
            s["total"] += e["dur"]
            s["min"] = min(s["min"], e["dur"])
            s["max"] = max(s["max"], e["dur"])
        if reset:
            _events.clear()
    for s in stats.values():
        s["avg"] = s["total"] / max(s["count"], 1)
    if format == "json":
        return json.dumps(stats)
    key = {"total": "total", "avg": "avg", "min": "min", "max": "max",
           "count": "count"}.get(sort_by, "total")
    rows = sorted(stats.items(), key=lambda kv: kv[1][key],
                  reverse=not ascending)
    lines = ["%-40s %8s %12s %12s %12s %12s"
             % ("Name", "Calls", "Total(us)", "Avg(us)", "Min(us)",
                "Max(us)")]
    for name, s in rows:
        lines.append("%-40s %8d %12.1f %12.1f %12.1f %12.1f"
                     % (name[:40], s["count"], s["total"], s["avg"],
                        s["min"], s["max"]))
    return "\n".join(lines)


class Domain:
    """Named grouping for custom profiling objects (parity: :225)."""

    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    _tid = 1

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._start = None
        cls = _Span
        self._tid_id = cls._tid
        cls._tid = cls._tid + 1

    def start(self):
        self._start = _now_us()

    def stop(self):
        if self._start is None:
            return
        start, self._start = self._start, None
        if not _recording():  # same gate as add_span
            return
        with _lock:
            _events.append({
                "name": self.name, "ph": "X",
                "cat": str(self.domain), "ts": start,
                "dur": _now_us() - start,
                "pid": 0, "tid": self._tid_id,
            })

    def __str__(self):
        return self.name


class Task(_Span):
    """Nestable named span (parity: :284)."""


class Frame(_Span):
    """Per-iteration span, e.g. one training step (parity: :326)."""


class Counter:
    """Numeric time-series value (parity: :368); chrome 'C' events."""

    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value  # value tracks even while not recording
        if not _recording():  # same gate as add_span
            return
        with _lock:
            _events.append({"name": self.name, "ph": "C",
                            "ts": _now_us(), "pid": 0,
                            "args": {self.name: value}})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self

    def __str__(self):
        return self.name


class Marker:
    """Instant event (parity: :430); chrome 'i' events."""

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if not _recording():  # same gate as add_span
            return
        with _lock:
            _events.append({"name": self.name, "ph": "i",
                            "ts": _now_us(), "pid": 0, "tid": 0,
                            "s": {"process": "p", "thread": "t",
                                  "global": "g"}.get(scope, "p")})

    def __str__(self):
        return self.name


_kv_handle = [None]


def set_kvstore_handle(handle):
    """Attach a dist KVStore so ``profile_process='server'`` commands
    reach the servers (parity: profiler.py set_kvstore_handle)."""
    _kv_handle[0] = handle


def _require_kv_handle():
    h = _kv_handle[0]
    if h is None or not hasattr(h, "set_server_profiler_state"):
        raise RuntimeError(
            "profile_process='server' needs a dist kvstore: call "
            "mx.profiler.set_kvstore_handle(kv) with a dist_* store first")
    return h


# parity: MXNET_PROFILER_AUTOSTART (env_var.md) — begin collecting as
# soon as the process imports the framework
import os as _os  # noqa: E402

if _os.environ.get("MXNET_PROFILER_AUTOSTART", "0") in ("1", "true"):
    set_state("run")
