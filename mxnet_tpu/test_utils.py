"""Testing harness: numeric-gradient and cross-context consistency checks.

Capability parity with the reference harness
(``python/mxnet/test_utils.py``): ``assert_almost_equal`` with max-violation
reporting (ref ``:534``), finite-difference ``check_numeric_gradient``
(ref ``:981``), golden-forward/backward checks ``check_symbolic_forward`` /
``check_symbolic_backward`` (ref ``:1124``, ``:1205``), and the
cross-device oracle ``check_consistency`` (ref ``:1422``) — the designated
TPU test pattern: bind the same symbol on a reference context (CPU,
float64) and the device under test and compare outputs and gradients.

TPU-native mechanism: instead of perturbing executor buffers in place
(the reference mutates ``executor.arg_arrays``), both sides are pure
functions built from the Symbol; the finite-difference loop re-runs ONE
jitted scalar projection ``f(args) = Σ out·proj`` under
``jax.experimental.enable_x64`` so the FD arithmetic happens in float64
even though the framework default is float32, and the analytic side is
the very same ``jax.vjp`` path the real executors use.
"""
from __future__ import annotations

import contextlib
import functools

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context, cpu, current_context
from . import ndarray as nd
from .ndarray.ndarray import NDArray
from . import random as _random

_DEFAULT_CTX = None

_DTYPE_RTOL = {np.dtype(np.float16): 1e-2,
               np.dtype("bfloat16") if hasattr(np, "bfloat16") else
               np.dtype(np.float16): 1e-2,
               np.dtype(np.float32): 1e-4,
               np.dtype(np.float64): 1e-7}
_DTYPE_ATOL = {np.dtype(np.float16): 1e-3,
               np.dtype(np.float32): 1e-5,
               np.dtype(np.float64): 1e-9}


def default_context():
    """The context tests run on (ref test_utils.py:58)."""
    return _DEFAULT_CTX or current_context()


def set_default_context(ctx):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def default_dtype():
    return np.float32


def _np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    if isinstance(a, jax.Array):
        return np.asarray(a)
    return np.asarray(a)


def get_rtol(rtol=None, dtype=None):
    if rtol is not None:
        return rtol
    if dtype is not None:
        return _DTYPE_RTOL.get(np.dtype(dtype), 1e-5)
    return 1e-5


def get_atol(atol=None, dtype=None):
    if atol is not None:
        return atol
    if dtype is not None:
        return _DTYPE_ATOL.get(np.dtype(dtype), 1e-20)
    return 1e-20


def random_arrays(*shapes):
    """Random float32 numpy arrays; scalar for () shapes (ref :95)."""
    arrays = [np.array(np.random.randn(), dtype=np.float32) if len(s) == 0
              else np.random.randn(*s).astype(np.float32) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def rand_shape_nd(num_dim, dim=10, allow_zero_size=False):
    low = 0 if allow_zero_size else 1
    return tuple(np.random.randint(low, dim + 1, size=num_dim))


def rand_shape_2d(dim0=10, dim1=10, allow_zero_size=False):
    return rand_shape_nd(2, max(dim0, dim1), allow_zero_size)


def rand_shape_3d(dim0=10, dim1=10, dim2=10, allow_zero_size=False):
    return rand_shape_nd(3, max(dim0, dim1, dim2), allow_zero_size)


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, scale=1.0):
    """Random NDArray (dense; row_sparse/csr via ndarray.sparse)."""
    dtype = dtype or default_dtype()
    data = (np.random.uniform(-scale, scale, size=shape)).astype(dtype)
    if stype == "default":
        return nd.array(data, ctx=ctx)
    from .ndarray import sparse as _sp
    density = 0.1 if density is None else density
    mask = np.random.uniform(size=shape) < density
    data = data * mask
    if stype == "row_sparse":
        return _sp.RowSparseNDArray.from_dense(nd.array(data, ctx=ctx))
    if stype == "csr":
        return _sp.CSRNDArray.from_dense(nd.array(data, ctx=ctx))
    raise MXNetError("unknown storage type %r" % stype)


def same(a, b):
    return np.array_equal(_np(a), _np(b))


def find_max_violation(a, b, rtol=None, atol=None):
    """Index/value of the worst |a-b| - (atol + rtol|b|) violation (ref :492)."""
    a, b = _np(a), _np(b)
    rtol, atol = get_rtol(rtol), get_atol(atol)
    diff = np.abs(a - b)
    tol = atol + rtol * np.abs(b)
    violation = diff / (tol + 1e-300)
    idx = np.unravel_index(np.argmax(violation), violation.shape)
    return idx, np.max(violation)


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    return np.allclose(_np(a), _np(b), rtol=get_rtol(rtol),
                       atol=get_atol(atol), equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Allclose with a max-violation error message (ref :534)."""
    a_np, b_np = _np(a), _np(b)
    if a_np.shape != b_np.shape:
        raise AssertionError(
            "shape mismatch: %s %s vs %s %s"
            % (names[0], a_np.shape, names[1], b_np.shape))
    if np.allclose(a_np, b_np, rtol=get_rtol(rtol), atol=get_atol(atol),
                   equal_nan=equal_nan):
        return
    idx, rel = find_max_violation(a_np, b_np, rtol, atol)
    raise AssertionError(
        "%s and %s differ: max violation %.3g x tolerance at index %s "
        "(%s=%r, %s=%r); rtol=%g atol=%g"
        % (names[0], names[1], rel, idx,
           names[0], a_np[idx], names[1], b_np[idx],
           get_rtol(rtol), get_atol(atol)))


def assert_exception(f, exception_type, *args, **kwargs):
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError("did not raise %s" % exception_type)


def with_seed(seed=None):
    """Decorator seeding mx+numpy per test, logging the seed on failure
    (reference tests/python/unittest/common.py:155)."""
    import functools as _ft

    def deco(f):
        @_ft.wraps(f)
        def wrapper(*args, **kwargs):
            import random as _pyrandom

            actual = (np.random.randint(0, np.iinfo(np.int32).max)
                      if seed is None else seed)
            from . import random as _mxrandom

            _mxrandom.seed(actual)
            np.random.seed(actual)
            _pyrandom.seed(actual)
            try:
                return f(*args, **kwargs)
            except Exception:
                print("*** test failed with seed %d: rerun with "
                      "with_seed(%d) to reproduce ***" % (actual, actual))
                raise
        return wrapper
    return deco


def retry(n):
    """Retry a flaky (randomized) test up to n times (ref common.py)."""
    def deco(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
        return wrapper
    return deco


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Feed inputs by name, return outputs as numpy (ref :754)."""
    outs = sym.eval(ctx=ctx, **{k: nd.array(v) for k, v in inputs.items()})
    outs = [o.asnumpy() for o in outs]
    return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# location parsing
# ---------------------------------------------------------------------------

def _parse_location(sym, location, dtype=np.float64):
    """list-or-dict of arrays → dict name→np array (ref :782)."""
    arg_names = sym.list_arguments()
    if isinstance(location, dict):
        unknown = set(location) - set(arg_names)
        if unknown:
            raise MXNetError("unknown arguments %s" % sorted(unknown))
        loc = dict(location)
    else:
        if len(location) != len(arg_names):
            raise MXNetError(
                "expected %d args (%s), got %d"
                % (len(arg_names), arg_names, len(location)))
        loc = dict(zip(arg_names, location))
    out = {}
    for k, v in loc.items():
        v = _np(v)
        out[k] = v.astype(dtype) if np.issubdtype(v.dtype, np.floating) \
            else v
    return out


def _parse_aux_states(sym, aux_states, dtype=np.float64):
    aux_names = sym.list_auxiliary_states()
    if aux_states is None:
        return {}
    if isinstance(aux_states, dict):
        aux = dict(aux_states)
    else:
        aux = dict(zip(aux_names, aux_states))
    out = {}
    for k, v in aux.items():
        v = _np(v)
        out[k] = v.astype(dtype) if np.issubdtype(v.dtype, np.floating) \
            else v
    return out


@contextlib.contextmanager
def _x64():
    # jax moved/removed the top-level alias; the supported spelling is
    # jax.experimental.enable_x64 (present since 0.4.x).
    from jax.experimental import enable_x64

    with enable_x64(True):
        yield


def _project_fn(sym, bindings_names, projs, mode="train"):
    """Scalar f(grad_args, other_args) = Σ_i sum(out_i · proj_i)."""
    raw = sym._make_fn(bindings_names, mode=mode)

    def scalar(grad_args, other_args, key):
        with _random.trace_key_scope(key):
            b = dict(other_args)
            b.update(grad_args)
            outs = raw(b)
        total = 0.0
        for o, p in zip(outs, projs):
            total = total + jnp.sum(o.astype(jnp.float64) * p)
        return total

    return scalar


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-4,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None,
                           dtype=np.float64):
    """Finite-difference check of the backward pass (ref :981).

    Projects the outputs to a scalar with a fixed random cotangent, then
    compares ``jax.grad`` of that scalar (the same vjp machinery the
    executors use) against central finite differences computed in float64.
    """
    location = _parse_location(sym, location, dtype)
    aux = _parse_aux_states(sym, aux_states, dtype)
    arg_names = sym.list_arguments()
    if grad_nodes is None:
        grad_nodes = [n for n in arg_names
                      if np.issubdtype(location[n].dtype, np.floating)]
    elif isinstance(grad_nodes, dict):
        grad_nodes = [n for n, req in grad_nodes.items() if req != "null"]
    grad_nodes = list(grad_nodes)
    mode = "train" if use_forward_train else "predict"

    with _x64():
        key = jax.random.PRNGKey(0)
        # fixed random projection per output
        probe = sym._make_fn(sym.list_inputs(), mode=mode)
        all_bind = dict(location)
        all_bind.update(aux)
        with _random.trace_key_scope(key):
            outs = probe({k: jnp.asarray(v) for k, v in all_bind.items()})
        rng = np.random.RandomState(42)
        projs = [jnp.asarray(rng.normal(size=np.shape(o)) + 0.1)
                 for o in outs]

        grad_args = {n: jnp.asarray(location[n]) for n in grad_nodes}
        other = {k: jnp.asarray(v) for k, v in all_bind.items()
                 if k not in set(grad_nodes)}
        scalar = _project_fn(sym, sym.list_inputs(), projs, mode)
        analytic = jax.jit(jax.grad(scalar))(grad_args, other, key)
        fwd = jax.jit(scalar)

        for name in grad_nodes:
            base = np.asarray(location[name], dtype=np.float64)
            num = np.zeros_like(base, dtype=np.float64)
            flat = base.ravel()
            for i in range(flat.size):
                for sgn in (1.0, -1.0):
                    pert = flat.copy()
                    pert[i] += sgn * numeric_eps
                    ga = dict(grad_args)
                    ga[name] = jnp.asarray(pert.reshape(base.shape))
                    num.ravel()[i] += sgn * float(fwd(ga, other, key))
            num /= 2 * numeric_eps
            assert_almost_equal(
                _np(analytic[name]), num, rtol=rtol, atol=atol,
                names=("analytic_grad_of_%s" % name,
                       "numeric_grad_of_%s" % name))


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None, equal_nan=False,
                           dtype=np.float32):
    """Compare forward outputs against expected numpy arrays (ref :1124)."""
    location = _parse_location(sym, location, dtype)
    aux = _parse_aux_states(sym, aux_states, dtype)
    args = {k: nd.array(v) for k, v in location.items()}
    args.update({k: nd.array(v) for k, v in aux.items()})
    outs = sym.eval(ctx=ctx, **args)
    if isinstance(expected, dict):
        expected = [expected[n] for n in sym.list_outputs()]
    for o, e in zip(outs, expected):
        assert_almost_equal(o.asnumpy(), _np(e), rtol=rtol, atol=atol,
                            names=("output", "expected"),
                            equal_nan=equal_nan)
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, equal_nan=False, dtype=np.float32):
    """Compare backward gradients against expected numpy arrays (ref :1205)."""
    location = _parse_location(sym, location, dtype)
    aux = _parse_aux_states(sym, aux_states, dtype)
    ctx = ctx or default_context()
    args = {k: nd.array(v) for k, v in location.items()}
    auxs = {k: nd.array(v) for k, v in aux.items()}
    if isinstance(grad_req, str):
        reqs = {n: grad_req for n in sym.list_arguments()}
    elif isinstance(grad_req, (list, tuple)):
        reqs = dict(zip(sym.list_arguments(), grad_req))
    else:
        reqs = dict(grad_req)
    exe = sym.bind(ctx=ctx, args=args, grad_req=reqs)
    for n, arr in auxs.items():
        exe.aux_dict[n]._set_data(arr.data())
    exe.forward(is_train=True)
    if isinstance(out_grads, (nd.NDArray, np.ndarray)):
        out_grads = [out_grads]
    if isinstance(out_grads, dict):
        out_grads = [out_grads[n] for n in sym.list_outputs()]
    exe.backward([g if isinstance(g, NDArray) else nd.array(g)
                  for g in out_grads])
    if isinstance(expected, dict):
        items = expected.items()
    else:
        items = zip(sym.list_arguments(), expected)
    grads = {}
    for name, e in items:
        if e is None or reqs.get(name, "null") == "null":
            continue
        g = exe.grad_dict[name].asnumpy()
        grads[name] = g
        assert_almost_equal(g, _np(e), rtol=rtol, atol=atol,
                            names=("grad_of_%s" % name, "expected"),
                            equal_nan=equal_nan)
    return grads


def get_tolerance(rtol, ctx=None, dtype=np.float32):
    return max(rtol or 0, get_rtol(None, dtype))


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, ground_truth=None,
                      equal_nan=False, use_uniform=False,
                      rand_type=np.float64):
    """Bind the same symbol on several contexts/dtypes, compare (ref :1422).

    ``ctx_list`` entries: ``{'ctx': Context, 'type_dict': {name: dtype},
    <name>: shape, ...}``.  The most precise entry is the oracle — the
    designated CPU-reference-vs-TPU test pattern (SURVEY §4.2).
    """
    if tol is None:
        tol = {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-3,
               np.dtype(np.float64): 1e-5, np.dtype(np.uint8): 0,
               np.dtype(np.int32): 0, np.dtype(np.int64): 0}
    elif isinstance(tol, (int, float)):
        tol = {np.dtype(t): tol for t in
               (np.float16, np.float32, np.float64, np.uint8, np.int32,
                np.int64)}
    syms = sym if isinstance(sym, list) else [sym] * len(ctx_list)
    arg_names = syms[0].list_arguments()

    # generate shared f64 input data from the first spec
    spec0 = ctx_list[0]
    shapes = {k: v for k, v in spec0.items()
              if k not in ("ctx", "type_dict")}
    if use_uniform:
        data = {n: np.random.uniform(-scale, scale, size=s)
                .astype(rand_type) for n, s in shapes.items()}
    else:
        data = {n: (np.random.normal(size=s) * scale).astype(rand_type)
                for n, s in shapes.items()}
    if arg_params:
        data.update({k: _np(v).astype(rand_type)
                     for k, v in arg_params.items()})
    for n in arg_names:
        if n not in data:
            raise MXNetError("check_consistency: no shape for arg %r" % n)

    results = []
    for s, spec in zip(syms, ctx_list):
        ctx = spec.get("ctx", default_context())
        type_dict = spec.get("type_dict", {})
        args = {n: nd.array(data[n].astype(type_dict.get(n, np.float32)),
                            ctx=ctx) for n in arg_names}
        exe = s.bind(ctx=ctx, args=args, grad_req=grad_req)
        if aux_params:
            for n, v in aux_params.items():
                exe.aux_dict[n]._set_data(nd.array(v).data())
        exe.forward(is_train=(grad_req != "null"))
        outs = [o.asnumpy().astype(np.float64) for o in exe.outputs]
        grads = {}
        if grad_req != "null":
            exe.backward([nd.array(np.ones(o.shape, np.float32))
                          for o in exe.outputs])
            grads = {n: g.asnumpy().astype(np.float64)
                     for n, g in exe.grad_dict.items() if g is not None}
        dtypes = [np.dtype(type_dict.get(n, np.float32))
                  for n in arg_names] or [np.dtype(np.float32)]
        max_dt = max(dtypes, key=lambda d: d.itemsize)
        results.append((outs, grads, max_dt))

    if ground_truth is None:
        gt_idx = max(range(len(results)),
                     key=lambda i: results[i][2].itemsize)
        gt_outs, gt_grads, _ = results[gt_idx]
    else:
        gt_outs, gt_grads = ground_truth, {}

    errors = []
    for i, (outs, grads, dt) in enumerate(results):
        t = tol.get(dt, 1e-3)
        for j, (o, g) in enumerate(zip(outs, gt_outs)):
            try:
                assert_almost_equal(o, g, rtol=t, atol=t,
                                    names=("ctx%d_out%d" % (i, j), "gt"),
                                    equal_nan=equal_nan)
            except AssertionError as e:
                errors.append(str(e))
        for n, g in grads.items():
            if n in gt_grads:
                try:
                    assert_almost_equal(
                        g, gt_grads[n], rtol=t, atol=t,
                        names=("ctx%d_grad_%s" % (i, n), "gt"),
                        equal_nan=equal_nan)
                except AssertionError as e:
                    errors.append(str(e))
    if errors and raise_on_err:
        raise AssertionError("check_consistency failed:\n"
                             + "\n".join(errors))
    return [r[0] for r in results]
