"""Persistent cross-process compilation cache + AOT warm start.

TPU-native answer to the reference's deployment story (the C predict API and
``amalgamation/``: load an artifact, run immediately, no frontend).  Under
XLA every process pays trace+compile for every executable it touches — a
cold llama train step is ~2 minutes of compile — so this module provides
two escape hatches, wired under every compile site the framework has
(``_jitted`` eager ops, bulk segments, ``hybridize()``'d blocks,
``JitTrainStep``, ``deploy.export_model``):

1. **Persistent compilation cache** — JAX's disk cache, enabled and managed
   here.  ``MXNET_COMPILE_CACHE`` controls it: ``0`` disables, ``1`` forces
   on, a *path* forces on with that directory, and the default ``auto``
   enables it for accelerator processes only (XLA:CPU cache entries are AOT
   objects keyed without host machine features — an entry compiled
   elsewhere can SIGILL a pure-CPU process that loads it).
   ``MXNET_COMPILE_CACHE_DIR`` picks the directory (default
   ``$XDG_CACHE_HOME/mxnet_tpu/xla_cache``), ``MXNET_COMPILE_CACHE_MIN_SECS``
   the minimum compile time worth persisting, and
   ``MXNET_COMPILE_CACHE_BUDGET_MB`` an LRU size budget enforced here (not
   via jax's own ``jax_compilation_cache_max_size``) so evictions are
   *countable*.  Hit/miss/write/evict counters and a size gauge export
   through telemetry as ``mxnet_compile_cache_*``.

2. **AOT executable serialization** — ``serialize_compiled`` /
   ``deserialize_compiled`` wrap PJRT executable pickling
   (``jax.experimental.serialize_executable``) and ``save_bundle`` /
   ``load_bundle`` give ``hybridize(aot=...)``, ``JitTrainStep
   .save_executable`` and ``Predictor.warm()`` a common signed artifact
   format, so a fleet restart compiles *nothing*.

Keying notes: bulk segments are structurally keyed by op sequence in
``engine.py``; the exact O0 taped path compiles through
``lower().compile(compiler_options=...)`` under a *differently named*
traced callable, so O0 and O2 artifacts can never collide in the disk
cache (the HLO module name and the compiler options both enter jax's
cache key).
"""
from __future__ import annotations

import atexit
import os
import pickle
import threading

from .base import MXNetError, atomic_path
from .testing import lockcheck as _lockcheck

_AOT_MAGIC = b"MXAOT1\n"

_lock = _lockcheck.named_lock("compile.cache")
# raw monitoring-event tallies; "misses" is derived (requests - hits)
_stats = {"hits": 0, "writes": 0, "requests": 0, "evictions": 0,
          "aot_loads": 0, "aot_saves": 0}
_state = {"enabled": False, "dir": None, "budget_mb": 0.0,
          "listener": False, "collector": False, "atexit": False}


def default_cache_dir():
    base = (os.environ.get("XDG_CACHE_HOME")
            or os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "mxnet_tpu", "xla_cache")


def enabled():
    """True when the persistent disk cache was activated by configure()."""
    return _state["enabled"]


def cache_dir():
    """The active cache directory, or None when disabled."""
    return _state["dir"] if _state["enabled"] else None


def persistent_hits():
    """Monotonic count of executables loaded from the disk cache.

    Cheap enough for the dispatch hot path: engine/registry snapshot it
    around a push to tell a disk hit (fast, warm start) from a true
    retrace, so warm processes neither pollute ``mxnet_compile_seconds``
    nor trip the MXNET_RETRACE_WARN_THRESHOLD watchdog.
    """
    return _stats["hits"]


def stats():
    with _lock:
        out = dict(_stats)
    out["misses"] = max(0, out["requests"] - out["hits"])
    return out


def cache_size_bytes():
    d = _state["dir"]
    if not d or not os.path.isdir(d):
        return 0
    total = 0
    try:
        for ent in os.scandir(d):
            try:
                if ent.is_file():
                    total += ent.stat().st_size
            except OSError:
                continue
    except OSError:
        return 0
    return total


def _listener(event, **kwargs):
    # jax emits these from compiler.py/compilation_cache.py:
    #   cache_hits                 -> executable deserialized from disk
    #   cache_misses               -> entry WRITTEN to disk (fired on put)
    #   compile_requests_use_cache -> any compile request with cache on
    if not event.startswith("/jax/compilation_cache/"):
        return
    with _lock:
        if event.endswith("/cache_hits"):
            _stats["hits"] += 1
        elif event.endswith("/cache_misses"):
            _stats["writes"] += 1
        elif event.endswith("/compile_requests_use_cache"):
            _stats["requests"] += 1


def _collector():
    from .telemetry import metrics as _m

    snap = stats()
    _m.counter("mxnet_compile_cache_hits_total",
               "Executables loaded from the persistent compile cache"
               ).set(snap["hits"])
    _m.counter("mxnet_compile_cache_misses_total",
               "Compile requests the persistent cache could not serve"
               ).set(snap["misses"])
    _m.counter("mxnet_compile_cache_writes_total",
               "Executables written to the persistent compile cache"
               ).set(snap["writes"])
    _m.counter("mxnet_compile_cache_evictions_total",
               "Cache entries evicted by MXNET_COMPILE_CACHE_BUDGET_MB"
               ).set(snap["evictions"])
    _m.counter("mxnet_compile_cache_aot_loads_total",
               "AOT executables deserialized from bundles"
               ).set(snap["aot_loads"])
    _m.counter("mxnet_compile_cache_aot_saves_total",
               "AOT executables serialized into bundles"
               ).set(snap["aot_saves"])
    if _state["enabled"]:
        _m.gauge("mxnet_compile_cache_size_bytes",
                 "Total bytes in the persistent compile cache directory"
                 ).set(cache_size_bytes())


def _ensure_observability():
    if not _state["listener"]:
        try:
            from jax._src import monitoring

            monitoring.register_event_listener(_listener)
            _state["listener"] = True
        except Exception:
            pass
    if not _state["collector"]:
        try:
            from .telemetry import metrics as _m

            _m.register_collector(_collector)
            _state["collector"] = True
        except Exception:
            pass


def enforce_budget(budget_mb=None):
    """Evict oldest-mtime cache entries until the directory fits the budget.

    Deliberately NOT delegated to jax's ``jax_compilation_cache_max_size``:
    jax evicts silently, and the whole point of owning eviction is the
    ``mxnet_compile_cache_evictions_total`` counter.  Returns the number of
    entries evicted.
    """
    if budget_mb is None:
        budget_mb = _state["budget_mb"]
    d = _state["dir"]
    if not budget_mb or budget_mb <= 0 or not d or not os.path.isdir(d):
        return 0
    budget = float(budget_mb) * 1024 * 1024
    # jax stores each executable as "<key>-cache" plus a tiny "<key>-atime"
    # companion it touches on every read; group the pair into one logical
    # entry, use the freshest mtime of the pair as its LRU recency, and
    # evict both files together so no orphans accumulate
    groups = {}
    try:
        for ent in os.scandir(d):
            try:
                if not ent.is_file():
                    continue
                st = ent.stat()
            except OSError:
                continue
            key = ent.name
            for suffix in ("-atime", "-cache"):
                if key.endswith(suffix):
                    key = key[: -len(suffix)]
                    break
            mtime, size, paths = groups.get(key, (0.0, 0, []))
            groups[key] = (max(mtime, st.st_mtime), size + st.st_size,
                           paths + [ent.path])
    except OSError:
        return 0
    total = sum(sz for _, sz, _ in groups.values())
    if total <= budget:
        return 0
    evicted = 0
    for _, sz, paths in sorted(groups.values()):  # least recently used first
        if total <= budget:
            break
        removed = False
        for path in paths:
            try:
                os.remove(path)
                removed = True
            except OSError:
                continue
        if removed:
            total -= sz
            evicted += 1
    if evicted:
        with _lock:
            _stats["evictions"] += evicted
    return evicted


def _looks_like_path(raw):
    return (os.sep in raw or raw.startswith(("~", ".", "$"))
            or (os.altsep and os.altsep in raw))


def configure(env=None):
    """Resolve the MXNET_COMPILE_CACHE* env contract and apply it to jax.

    Called once at ``import mxnet_tpu`` (before any compile can happen).
    Never raises: a cache is an optimization and must not break import.
    Returns True when the persistent cache ended up enabled.
    """
    if env is None:
        env = os.environ
    raw = env.get("MXNET_COMPILE_CACHE", "auto")
    mode = raw.lower()
    if mode in ("0", "false", "off", "no"):
        return False
    try:
        import jax

        dir_from_mode = None
        if mode not in ("1", "true", "on", "yes", "auto") \
                and _looks_like_path(raw):
            dir_from_mode = os.path.expandvars(os.path.expanduser(raw))
        forced = mode in ("1", "true", "on", "yes") or bool(dir_from_mode)

        cache_dir_ = (env.get("MXNET_COMPILE_CACHE_DIR") or dir_from_mode
                      or None)
        if not forced and not cache_dir_:
            # auto: default-on for ACCELERATOR processes only — XLA:CPU
            # cache entries are AOT objects keyed without host machine
            # features; an entry compiled elsewhere (e.g. through a device
            # tunnel's cpu staging platform) can SIGILL a pure-CPU process
            # that loads it (observed killing dist-kvstore servers).  CPU
            # compiles are cheap; TPU compiles are the minutes-long ones
            # worth persisting.  MXNET_COMPILE_CACHE=1 / a path value / an
            # explicit _DIR opts a CPU process in.
            plats = str(getattr(jax.config, "jax_platforms", "") or "")
            primary = plats.split(",")[0].strip() if plats else ""
            # unknown/unset platform counts as CPU: a host with no
            # accelerator plugin auto-selects cpu with an EMPTY config
            if primary in ("cpu", ""):
                return False
        if not cache_dir_:
            cache_dir_ = default_cache_dir()
        os.makedirs(cache_dir_, exist_ok=True)
        min_secs = float(env.get("MXNET_COMPILE_CACHE_MIN_SECS", "1.0"))
        jax.config.update("jax_compilation_cache_dir", cache_dir_)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _state["enabled"] = True
        _state["dir"] = cache_dir_
        try:
            _state["budget_mb"] = float(
                env.get("MXNET_COMPILE_CACHE_BUDGET_MB", "0") or "0")
        except ValueError:
            _state["budget_mb"] = 0.0
        _ensure_observability()
        enforce_budget()
        if not _state["atexit"]:
            atexit.register(enforce_budget)
            _state["atexit"] = True
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# AOT executable serialization (PJRT pickling + bundle format)
# ---------------------------------------------------------------------------

def serialize_compiled(compiled):
    """``jax.stages.Compiled`` -> opaque bytes (device-independent pickle)."""
    from jax.experimental import serialize_executable as _se

    payload, in_tree, out_tree = _se.serialize(compiled)
    return pickle.dumps(
        {"payload": payload, "in_tree": in_tree, "out_tree": out_tree},
        protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_compiled(blob, backend=None):
    """Inverse of :func:`serialize_compiled`; returns a callable Compiled."""
    from jax.experimental import serialize_executable as _se

    try:
        doc = pickle.loads(blob)
        out = _se.deserialize_and_load(doc["payload"], doc["in_tree"],
                                       doc["out_tree"], backend=backend)
    except MXNetError:
        raise
    except Exception as e:
        raise MXNetError(
            "failed to deserialize AOT executable (%s: %s) — bundles are "
            "only loadable on the jax version/backend that produced them"
            % (type(e).__name__, e))
    with _lock:
        _stats["aot_loads"] += 1
    # the aot_loads counter must be published even in processes where the
    # disk cache is off (CPU serving procs): configure() never ran
    # _ensure_observability there, so register the collector here too
    _ensure_observability()
    return out


def save_bundle(path, entries, meta=None):
    """Write an AOT bundle: ``{key: serialized-executable-bytes}`` + meta.

    Atomic (tmp + rename) so an interrupted save never corrupts a bundle a
    serving fleet is about to load.
    """
    import jax

    doc = {
        "jax_version": jax.__version__,
        "platform": jax.default_backend(),
        "meta": dict(meta or {}),
        "entries": dict(entries),
    }
    with atomic_path(path) as tmp:
        with open(tmp, "wb") as f:
            f.write(_AOT_MAGIC)
            pickle.dump(doc, f, protocol=pickle.HIGHEST_PROTOCOL)
    with _lock:
        _stats["aot_saves"] += len(doc["entries"])
    _ensure_observability()


def load_bundle(path):
    """Read an AOT bundle; validates magic + platform before any load."""
    import jax

    try:
        with open(path, "rb") as f:
            magic = f.read(len(_AOT_MAGIC))
            if magic != _AOT_MAGIC:
                raise MXNetError(
                    "%s is not an mxnet_tpu AOT bundle (bad magic)" % path)
            doc = pickle.load(f)
    except MXNetError:
        raise
    except Exception as e:
        raise MXNetError("failed to read AOT bundle %s (%s: %s)"
                         % (path, type(e).__name__, e))
    plat = doc.get("platform")
    if plat and plat != jax.default_backend():
        raise MXNetError(
            "AOT bundle %s was compiled for platform %r but this process "
            "runs %r — recompile or re-export on the target platform"
            % (path, plat, jax.default_backend()))
    ver = doc.get("jax_version")
    if ver and ver != jax.__version__:
        import warnings

        warnings.warn(
            "AOT bundle %s was produced under jax %s (running %s); "
            "deserialization may fail across versions"
            % (path, ver, jax.__version__))
    return doc
