"""CC6xx — collective-consistency checks for the parallel layer.

Two halves share one rule vocabulary:

* a **static AST pass** (:func:`run`, wired into the mxlint driver) that
  checks literal collective programs against meshes it can see being
  built in the same module (``make_mesh({...})`` / ``global_mesh({...})``
  / ``Mesh(devs, (...))``) — unknown ``axis_name`` strings (CC601),
  non-permutation literal ``ppermute`` perms (CC602), and collectives
  under data-dependent branches (CC603, the classic SPMD deadlock);
* **runtime pre-dispatch validators** (:func:`check_axis`,
  :func:`check_ppermute`) called by ``parallel/pipeline.py``, ``moe.py``
  and ``ring_attention.py`` before building a shard_map program, raising
  ``MXNetError`` with the same CC6xx vocabulary.  CC604 (pipeline
  geometry) and CC605 (kvstore key divergence) live entirely in their
  runtime call sites — their inputs are never module-level literals.

The static pass is deliberately conservative: axis names that are Python
variables, meshes built from runtime device counts, and perms built by
comprehension are all skipped, never guessed at.  CC601 only fires in a
module that builds at least one statically-known mesh, and ``P()`` spec
literals are only checked inside ``shard_map(...)`` call arguments —
free-standing ``PartitionSpec`` values (e.g. for ``device_put``) are out
of scope.
"""
from __future__ import annotations

import ast

from .findings import Finding
from .tracing_safety import _dotted

# lax collectives that take an axis name; value = positional index of it
_AXIS_ARG_POS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
    "ppermute": 1, "pshuffle": 1,
    "all_gather": 1, "all_to_all": 1, "psum_scatter": 1,
    "axis_index": 0,
}

_LAX_PREFIXES = ("lax", "jax.lax")


def _is_collective(fname):
    """'lax.psum' / 'jax.lax.ppermute' -> the op's short name, else None."""
    parts = fname.rsplit(".", 1)
    if len(parts) == 2 and parts[0] in _LAX_PREFIXES \
            and parts[1] in _AXIS_ARG_POS:
        return parts[1]
    return None


def _literal_strs(node):
    """[(string, ast_node)] for a Constant str or tuple/list of them."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [(node.value, node)]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append((e.value, e))
        return out
    return []


def _axis_arg(call, op):
    """The axis-name argument node of a collective call, or None."""
    pos = _AXIS_ARG_POS[op]
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _literal_perm(node):
    """[(src, dst)] for a literal list/tuple of int pairs, else None."""
    if not isinstance(node, (ast.List, ast.Tuple)):
        return None
    pairs = []
    for e in node.elts:
        if not (isinstance(e, (ast.Tuple, ast.List)) and len(e.elts) == 2):
            return None
        s, d = e.elts
        if not (isinstance(s, ast.Constant) and isinstance(s.value, int)
                and isinstance(d, ast.Constant)
                and isinstance(d.value, int)):
            return None
        pairs.append((s.value, d.value))
    return pairs


def _collect_meshes(tree):
    """Statically-known meshes: var name -> {axis: size|None}, plus the
    union over all of them (for collectives whose mesh isn't named)."""
    per_var, union = {}, {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        fname = _dotted(node.value.func)
        short = fname.rsplit(".", 1)[-1]
        axes = None
        if short in ("make_mesh", "global_mesh") and node.value.args:
            spec = node.value.args[0]
            if isinstance(spec, ast.Dict) and all(
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str) for k in spec.keys):
                axes = {}
                for k, v in zip(spec.keys, spec.values):
                    axes[k.value] = (v.value if isinstance(v, ast.Constant)
                                     and isinstance(v.value, int) else None)
        elif short == "Mesh" and len(node.value.args) >= 2:
            names = _literal_strs(node.value.args[1])
            if names:
                axes = {n: None for n, _ in names}
        if axes:
            per_var[node.targets[0].id] = axes
            for a, sz in axes.items():
                union.setdefault(a, sz)
    return per_var, union


def _mentions(test, params):
    return any(isinstance(n, ast.Name) and n.id in params
               for n in ast.walk(test))


def _is_none_check(test):
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops))


def _collectives_in(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            op = _is_collective(_dotted(sub.func))
            if op:
                yield sub, op


class _Pass:
    def __init__(self, path, tree, findings):
        self.path = path
        self.tree = tree
        self.findings = findings
        self.meshes, self.known = _collect_meshes(tree)
        self.local_defs = {n.name: n for n in ast.walk(tree)
                           if isinstance(n, ast.FunctionDef)}
        self._flagged = set()

    def flag(self, node, rule, message):
        key = (node.lineno, getattr(node, "col_offset", 0), rule)
        if key in self._flagged:
            return
        self._flagged.add(key)
        self.findings.append(Finding(self.path, node.lineno,
                                     getattr(node, "col_offset", 0),
                                     rule, message))

    def run(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            op = _is_collective(fname)
            if op:
                self._check_collective(node, op)
            short = fname.rsplit(".", 1)[-1]
            if short == "cond" and fname.rsplit(".", 1)[0] in _LAX_PREFIXES:
                self._check_cond_branches(node)
            elif short == "switch" \
                    and fname.rsplit(".", 1)[0] in _LAX_PREFIXES:
                self._check_switch_branches(node)
            elif short == "shard_map":
                self._check_shard_map(node)
        return self.findings

    # -- CC601 + CC602 on one collective call -----------------------------
    def _check_collective(self, call, op):
        axis_node = _axis_arg(call, op)
        axis_size = None
        if axis_node is not None and self.known:
            for name, strnode in _literal_strs(axis_node):
                if name not in self.known:
                    self.flag(strnode, "CC601",
                              "%s over axis %r, but the meshes built in "
                              "this module only define axes %s — dispatch "
                              "will fail (or deadlock a multihost job "
                              "waiting on peers that never enter)"
                              % (op, name, sorted(self.known)))
                elif self.known.get(name) is not None:
                    axis_size = self.known[name]
        elif axis_node is not None:
            lits = _literal_strs(axis_node)
            if len(lits) == 1:
                axis_size = None  # axis unknown, size unknowable
        if op != "ppermute":
            return
        perm_node = None
        for kw in call.keywords:
            if kw.arg == "perm":
                perm_node = kw.value
        if perm_node is None and len(call.args) > 2:
            perm_node = call.args[2]
        if perm_node is None:
            return
        pairs = _literal_perm(perm_node)
        if pairs is None:
            return
        problems = []
        srcs = [s for s, _ in pairs]
        dsts = [d for _, d in pairs]
        dup_src = sorted({s for s in srcs if srcs.count(s) > 1})
        dup_dst = sorted({d for d in dsts if dsts.count(d) > 1})
        if dup_src:
            problems.append("duplicate source rank(s) %s" % dup_src)
        if dup_dst:
            problems.append("duplicate destination rank(s) %s — those "
                            "lanes silently receive zeros" % dup_dst)
        if axis_size is not None:
            bad = sorted({r for r in srcs + dsts
                          if not 0 <= r < axis_size})
            if bad:
                problems.append("rank(s) %s out of range for axis of "
                                "size %d" % (bad, axis_size))
        if problems:
            self.flag(perm_node, "CC602",
                      "ppermute perm %s is not a permutation: %s"
                      % (pairs, "; ".join(problems)))

    # -- CC603: collectives inside cond/switch branch functions -----------
    def _branch_fns(self, exprs):
        for e in exprs:
            if isinstance(e, ast.Lambda):
                yield e
            elif isinstance(e, ast.Name) and e.id in self.local_defs:
                yield self.local_defs[e.id]

    def _flag_branch_collectives(self, fn, where):
        for call, op in _collectives_in(fn):
            self.flag(call, "CC603",
                      "%s inside a %s branch: only the taken branch's "
                      "program runs per device, so devices disagreeing "
                      "on the predicate deadlock the collective — hoist "
                      "it out of the branch or make the predicate "
                      "replicated" % (op, where))

    def _check_cond_branches(self, call):
        exprs = list(call.args[1:3])
        exprs += [kw.value for kw in call.keywords
                  if kw.arg in ("true_fun", "false_fun")]
        for fn in self._branch_fns(exprs):
            self._flag_branch_collectives(fn, "lax.cond")

    def _check_switch_branches(self, call):
        if len(call.args) > 1 and isinstance(call.args[1],
                                             (ast.List, ast.Tuple)):
            for fn in self._branch_fns(call.args[1].elts):
                self._flag_branch_collectives(fn, "lax.switch")

    # -- shard_map: spec-literal CC601 + branchy-body CC603 ----------------
    def _shard_map_axes(self, call):
        for kw in call.keywords:
            if kw.arg == "mesh" and isinstance(kw.value, ast.Name):
                axes = self.meshes.get(kw.value.id)
                if axes:
                    return axes
        return self.known

    def _check_shard_map(self, call):
        axes = self._shard_map_axes(call)
        if axes:
            for kw in call.keywords:
                if kw.arg not in ("in_specs", "out_specs"):
                    continue
                for sub in ast.walk(kw.value):
                    if not isinstance(sub, ast.Call):
                        continue
                    short = _dotted(sub.func).rsplit(".", 1)[-1]
                    if short not in ("P", "PartitionSpec"):
                        continue
                    for name, strnode in _literal_strs(
                            ast.Tuple(elts=list(sub.args))):
                        if name not in axes:
                            self.flag(strnode, "CC601",
                                      "shard_map %s names axis %r, but "
                                      "its mesh only defines axes %s"
                                      % (kw.arg, name, sorted(axes)))
        # body: collectives under a parameter-dependent Python branch
        fn = call.args[0] if call.args else None
        if isinstance(fn, ast.Call) \
                and _dotted(fn.func).rsplit(".", 1)[-1] == "partial" \
                and fn.args:
            fn = fn.args[0]
        if isinstance(fn, ast.Name):
            fn = self.local_defs.get(fn.id)
        if not isinstance(fn, (ast.FunctionDef, ast.Lambda)):
            return
        params = {a.arg for a in fn.args.args}
        for stmt in ast.walk(fn):
            if not isinstance(stmt, (ast.If, ast.While)):
                continue
            if _is_none_check(stmt.test) \
                    or not _mentions(stmt.test, params):
                continue
            for call_, op in _collectives_in(stmt):
                self.flag(call_, "CC603",
                          "%s under a Python branch on a shard_map "
                          "parameter: per-device data can disagree on "
                          "the predicate, so some devices skip the "
                          "collective and the rest deadlock waiting "
                          "for them" % op)


def run(path, tree, findings=None):
    """Run the static CC pass over one parsed module."""
    if findings is None:
        findings = []
    return _Pass(path, tree, findings).run()


# ---------------------------------------------------------------------------
# runtime pre-dispatch validators (same vocabulary, raise instead of report)
# ---------------------------------------------------------------------------

def check_axis(mesh, axis_name, op="collective"):
    """Raise MXNetError (CC601) if ``axis_name`` is not a mesh axis."""
    from ..base import MXNetError

    names = tuple(getattr(mesh, "axis_names", ()))
    wanted = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    missing = [a for a in wanted if a not in names]
    if missing:
        raise MXNetError(
            "CC601 (unknown-axis-name): %s uses axis %s but the mesh only "
            "defines axes %s" % (op, missing if len(missing) > 1
                                 else repr(missing[0]), list(names)))


def check_ppermute(mesh, axis_name, perm, require_total=False,
                   op="ppermute"):
    """Raise MXNetError (CC602) unless ``perm`` is a valid (partial)
    permutation of ``range(mesh.shape[axis_name])``.

    ``require_total=False`` accepts partial permutations — gpipe's
    forward shift ``[(i, i+1) for i in range(n-1)]`` deliberately leaves
    the last stage without a destination.  Pass ``require_total=True``
    for rotations that must touch every rank.
    """
    from ..base import MXNetError

    check_axis(mesh, axis_name, op=op)
    n = dict(mesh.shape)[axis_name]
    pairs = [(int(s), int(d)) for s, d in perm]
    problems = []
    bad = sorted({r for p in pairs for r in p if not 0 <= r < n})
    if bad:
        problems.append("rank(s) %s out of range for axis %r of size %d"
                        % (bad, axis_name, n))
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    if len(set(srcs)) != len(srcs):
        problems.append("duplicate source rank(s) %s"
                        % sorted({s for s in srcs if srcs.count(s) > 1}))
    if len(set(dsts)) != len(dsts):
        problems.append("duplicate destination rank(s) %s"
                        % sorted({d for d in dsts if dsts.count(d) > 1}))
    if require_total and not problems and len(pairs) != n:
        problems.append("perm has %d pair(s) but axis %r has %d ranks and "
                        "require_total=True" % (len(pairs), axis_name, n))
    if problems:
        raise MXNetError(
            "CC602 (non-permutation-ppermute): %s perm %s over axis %r: %s"
            % (op, pairs, axis_name, "; ".join(problems)))
