"""CD11xx — concurrency discipline (pass 11, static side).

The five threaded tiers (serve scheduler/loop, HTTP front, dist kvstore
client+server, engine, telemetry) share one discipline: every piece of
cross-thread state has exactly one guarding lock, locks nest in one
global order, and nothing blocking or user-visible runs while a lock is
held.  This pass checks what the AST makes visible; the runtime half is
``mxnet_tpu/testing/lockcheck.py`` (``MXNET_LOCKCHECK=1``), which
watches the same contracts on live interleavings.

Per class, the pass first collects **lock attributes** — ``self._x =
threading.Lock()/RLock()/Condition(...)`` or the instrumented
``lockcheck.named_lock/named_rlock/named_condition`` forms.  A
``Condition(self._lock)`` sharing an existing lock attribute is an
*alias* of that lock (``with self._work`` holds ``self._lock``).  Then:

* **CD1101** ``unguarded-field-access`` — a *guarded* field (a
  majority of its non-``__init__`` accesses, two at minimum, hold a
  lock) is accessed with no lock held, in a method reachable
  from a thread entry point (``Thread(target=self.m)``, ``_loop_tick``,
  an HTTP ``do_*`` handler, or a server ``handle``/``_handle``).
* **CD1102** ``lock-order-inversion`` — the class's nested-``with``
  acquisition graph (including acquisitions reached through
  ``self.m()`` call edges, to a fixpoint) contains a cycle; reported
  once per cycle with both conflicting paths and their lines.
* **CD1103** ``blocking-call-under-lock`` — a blocking call while any
  lock is held: socket ``recv``/``recv_into``/``accept``,
  ``Future.result``, the host-sync set (``asnumpy``/``asscalar``/
  ``wait_to_read``/``block_until_ready``/``waitall`` — HS2xx's table),
  ``time.sleep``, or a condition ``.wait()`` **without a timeout**.  A
  *timed* wait on a condition is the one legitimate block-under-lock
  (it releases the lock; RB701 owns the no-deadline loop shape).
* **CD1104** ``acquire-without-finally`` — a manual ``<lock>.acquire()``
  statement not immediately followed by a ``try`` whose ``finally``
  releases the same lock: any exception in between leaks the lock
  forever.  ``with`` is the fix (or the canonical acquire/try/finally).
* **CD1105** ``callback-under-lock`` — resolving a user-visible future
  (``set_result``/``set_exception``), waking a user-facing done-event
  (``<x>_done.set()``/``<x>_event.set()``), or invoking a hook/callback
  while holding a lock: user code runs inside the critical section and
  can re-enter the scheduler (deadlock) or stretch the hold time
  unboundedly.  Resolve outside the lock, as
  ``serve/scheduler.py::_finish_slot`` does.

Everything is conservative in the usual mxlint way: locks, fields and
call edges are only believed when literally visible (``self.<attr>``
receivers, same-class calls), so dynamic dispatch and cross-object
locking produce no findings — the runtime sanitizer covers those.
"""
from __future__ import annotations

import ast

from .findings import Finding

# lock-constructor spellings recognized in `self._x = <ctor>(...)`
_LOCK_CTORS = frozenset({"Lock", "RLock", "named_lock", "named_rlock"})
_COND_CTORS = frozenset({"Condition", "named_condition"})

# CD1103 vocabulary: RB701/HS2xx's blocking tables, plus the wire calls
_BLOCKING_METHODS = frozenset({
    "recv", "recv_into", "accept", "result",           # socket / Future
    "asnumpy", "asscalar", "wait_to_read",             # host-sync pulls
    "block_until_ready",
})
_BLOCKING_FUNCS = frozenset({"waitall", "sleep"})

# CD1105 vocabulary
_CALLBACK_METHODS = frozenset({"set_result", "set_exception"})
_HOOK_WORDS = ("hook", "callback")
_EVENT_SUFFIXES = ("_done", "_event", "_ready")

# thread entry points: name-shaped (the serve loop, HTTP handlers, the
# socket server's per-connection handler)
_ENTRY_NAMES = frozenset({"_loop", "_loop_tick", "handle", "_handle",
                          "run", "serve_forever"})

_LOCKISH_WORDS = ("lock", "_lk", "mutex", "_cv", "cond", "sem")


def _call_name(call):
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _self_attr(node):
    """``self.<attr>`` -> attr name, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _lockish_name(name):
    low = name.lower()
    return any(w in low for w in _LOCKISH_WORDS)


class _ClassInfo:
    def __init__(self, node):
        self.node = node
        self.locks = {}         # attr -> canonical lock attr (aliases)
        self.methods = {}       # name -> FunctionDef
        self.entry_methods = set()


def _collect_class(cls):
    info = _ClassInfo(cls)
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[item.name] = item
    # lock attributes + condition aliases, wherever assigned
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        attr = _self_attr(node.targets[0])
        if attr is None or not isinstance(node.value, ast.Call):
            continue
        ctor = _call_name(node.value)
        if ctor in _LOCK_CTORS:
            info.locks[attr] = attr
        elif ctor in _COND_CTORS:
            # Condition(self._lock) aliases the shared lock; a bare
            # Condition() (or named_condition("x")) owns its own
            shared = None
            for arg in node.value.args:
                a = _self_attr(arg)
                if a is not None:
                    shared = a
                    break
            info.locks[attr] = shared if shared is not None else attr
    # thread entry points: name-shaped, Thread(target=self.m), and
    # HTTP do_* handlers
    for name, fn in info.methods.items():
        if name in _ENTRY_NAMES or name.startswith("do_"):
            info.entry_methods.add(name)
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and _call_name(node) == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    target = _self_attr(kw.value)
                    if target in info.methods:
                        info.entry_methods.add(target)
    return info


def _canonical(info, attr):
    """Resolve a lock attr through condition aliasing (one level)."""
    seen = set()
    while attr in info.locks and info.locks[attr] != attr \
            and attr not in seen:
        seen.add(attr)
        attr = info.locks[attr]
    return attr


class _MethodScan:
    """One pass over a method body tracking the held-lock stack."""

    def __init__(self, info, fn):
        self.info = info
        self.fn = fn
        # direct acquisitions: (lock, held_tuple, lineno, col)
        self.acquisitions = []
        # self-method calls: (name, held_tuple, lineno, col)
        self.calls = []
        # self.<field> accesses: (field, held?, lineno, col, is_store)
        self.accesses = []
        # blocking / callback calls under a held lock:
        # (kind_rule, lineno, col, detail)
        self.flagged = []
        # manual acquire statements: (node index context handled later)
        self._walk_body(fn.body, ())

    # -- helpers ---------------------------------------------------------
    def _lock_of_with_item(self, item):
        attr = _self_attr(item.context_expr)
        if attr is not None and attr in self.info.locks:
            return _canonical(self.info, attr)
        return None

    def _scan_expr(self, node, held):
        """Collect field accesses + flag blocking/callback calls in an
        expression subtree (no with/statement structure below here)."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                attr = _self_attr(sub)
                if attr is not None and attr not in self.info.locks:
                    store = isinstance(sub.ctx, (ast.Store, ast.Del))
                    self.accesses.append(
                        (attr, bool(held), sub.lineno, sub.col_offset,
                         store))
            if isinstance(sub, ast.Call):
                self._check_call(sub, held)

    def _check_call(self, call, held):
        name = _call_name(call)
        if name is None:
            return
        # self-method call edges (for CD1101 reachability + CD1102)
        fn = call.func
        if isinstance(fn, ast.Attribute) and \
                _self_attr(fn) is not None and name in self.info.methods:
            self.calls.append((name, tuple(held), call.lineno,
                               call.col_offset))
        if not held:
            return
        # CD1103: blocking call while holding a lock
        if isinstance(fn, ast.Attribute):
            if name in _BLOCKING_METHODS:
                self.flagged.append(("CD1103", call.lineno,
                                     call.col_offset,
                                     ".%s()" % name))
            elif name == "wait" and not call.args and \
                    not any(kw.arg == "timeout" for kw in call.keywords):
                # an untimed wait never comes back if the notifier died;
                # Event.wait() under someone ELSE's lock blocks it too
                self.flagged.append(("CD1103", call.lineno,
                                     call.col_offset,
                                     ".wait() with no timeout"))
        if name in _BLOCKING_FUNCS:
            self.flagged.append(("CD1103", call.lineno, call.col_offset,
                                 "%s()" % name))
        # CD1105: user-visible callback while holding a lock
        if isinstance(fn, ast.Attribute):
            if name in _CALLBACK_METHODS:
                self.flagged.append(("CD1105", call.lineno,
                                     call.col_offset, ".%s()" % name))
            elif name == "set" and any(
                    fn.value.attr.endswith(s) if isinstance(
                        fn.value, ast.Attribute) else
                    fn.value.id.endswith(s) if isinstance(
                        fn.value, ast.Name) else False
                    for s in _EVENT_SUFFIXES):
                self.flagged.append(
                    ("CD1105", call.lineno, call.col_offset,
                     "done-event .set()"))
            elif any(w in name.lower() for w in _HOOK_WORDS):
                self.flagged.append(("CD1105", call.lineno,
                                     call.col_offset, "%s()" % name))
        elif isinstance(fn, ast.Name) and \
                any(w in name.lower() for w in _HOOK_WORDS):
            self.flagged.append(("CD1105", call.lineno, call.col_offset,
                                 "%s()" % name))

    # -- statement walk --------------------------------------------------
    def _walk_body(self, body, held):
        for stmt in body:
            self._walk_stmt(stmt, held)

    def _walk_stmt(self, stmt, held):
        if isinstance(stmt, ast.With):
            inner = list(held)
            scanned = []
            for item in stmt.items:
                lock = self._lock_of_with_item(item)
                if lock is not None:
                    self.acquisitions.append(
                        (lock, tuple(inner), item.context_expr.lineno,
                         item.context_expr.col_offset))
                    inner.append(lock)
                else:
                    scanned.append(item.context_expr)
                if item.optional_vars is not None:
                    scanned.append(item.optional_vars)
            for expr in scanned:
                self._scan_expr(expr, held)
            self._walk_body(stmt.body, tuple(inner))
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs run later, not under this lock scope
            self._walk_body(stmt.body, ())
        elif isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test, held)
            self._walk_body(stmt.body, held)
            self._walk_body(stmt.orelse, held)
        elif isinstance(stmt, ast.For):
            self._scan_expr(stmt.target, held)
            self._scan_expr(stmt.iter, held)
            self._walk_body(stmt.body, held)
            self._walk_body(stmt.orelse, held)
        elif isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, held)
            for h in stmt.handlers:
                self._walk_body(h.body, held)
            self._walk_body(stmt.orelse, held)
            self._walk_body(stmt.finalbody, held)
        else:
            for node in ast.iter_child_nodes(stmt):
                self._scan_expr(node, held)


def _acquire_target(stmt):
    """``<x>.acquire(...)`` as a statement (Expr or single Assign):
    returns the receiver AST node, else None."""
    if isinstance(stmt, ast.Expr):
        call = stmt.value
    elif isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
        call = stmt.value
    else:
        return None
    if not isinstance(call, ast.Call) or \
            not isinstance(call.func, ast.Attribute) or \
            call.func.attr != "acquire":
        return None
    return call.func.value


def _releases_in_finally(stmt, recv_dump):
    """Does ``stmt`` (expected: Try) release ``recv_dump`` in finally?"""
    if not isinstance(stmt, ast.Try) or not stmt.finalbody:
        return False
    for node in ast.walk(ast.Module(body=stmt.finalbody,
                                    type_ignores=[])):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "release" and \
                ast.dump(node.func.value) == recv_dump:
            return True
    return False


class _Cd1104Checker(ast.NodeVisitor):
    """Module-wide: manual acquire() without the try/finally shape."""

    def __init__(self, path, findings, class_lock_attrs):
        self.path = path
        self.findings = findings
        self.class_lock_attrs = class_lock_attrs  # set of known attrs

    def _check_body(self, body):
        for i, stmt in enumerate(body):
            recv = _acquire_target(stmt)
            if recv is not None and self._lockish(recv):
                nxt = body[i + 1] if i + 1 < len(body) else None
                if nxt is None or not _releases_in_finally(
                        nxt, ast.dump(recv)):
                    self.findings.append(Finding(
                        self.path, stmt.lineno, stmt.col_offset,
                        "CD1104",
                        "manual %s.acquire() without an immediate "
                        "try/finally release: any exception before the "
                        "release leaks the lock forever — use `with`, "
                        "or `acquire(); try: ... finally: release()`"
                        % _recv_label(recv)))
        for stmt in body:
            for child_body in _child_bodies(stmt):
                self._check_body(child_body)

    def _lockish(self, recv):
        attr = _self_attr(recv)
        if attr is not None:
            return attr in self.class_lock_attrs or _lockish_name(attr)
        if isinstance(recv, ast.Name):
            return _lockish_name(recv.id)
        if isinstance(recv, ast.Attribute):
            return _lockish_name(recv.attr)
        return False

    def run(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_body(node.body)


def _child_bodies(stmt):
    for field in ("body", "orelse", "finalbody"):
        body = getattr(stmt, field, None)
        if body:
            yield body
    for h in getattr(stmt, "handlers", ()):
        yield h.body


def _recv_label(recv):
    attr = _self_attr(recv)
    if attr is not None:
        return "self.%s" % attr
    if isinstance(recv, ast.Name):
        return recv.id
    if isinstance(recv, ast.Attribute):
        return recv.attr
    return "<lock>"


def _check_class(path, cls, findings):
    info = _collect_class(cls)
    if not info.locks:
        return
    scans = {name: _MethodScan(info, fn)
             for name, fn in info.methods.items()}

    # ---- CD1103 / CD1105: flagged calls under a held lock --------------
    for scan in scans.values():
        for rule, lineno, col, detail in scan.flagged:
            if rule == "CD1103":
                findings.append(Finding(
                    path, lineno, col, "CD1103",
                    "blocking call %s while holding a lock: every other "
                    "thread needing that lock stalls behind the block "
                    "(and a dead peer wedges them forever) — move the "
                    "blocking call outside the critical section" % detail))
            else:
                findings.append(Finding(
                    path, lineno, col, "CD1105",
                    "user-visible callback (%s) while holding a lock: "
                    "user code runs inside the critical section and can "
                    "re-enter it (deadlock) or stretch the hold time — "
                    "collect under the lock, invoke after release"
                    % detail))

    # ---- CD1102: acquisition-order cycles ------------------------------
    # method -> all locks it (transitively) acquires, to a fixpoint
    acquires = {name: {lock for lock, _h, _l, _c in scan.acquisitions}
                for name, scan in scans.items()}
    changed = True
    while changed:
        changed = False
        for name, scan in scans.items():
            for callee, _held, _l, _c in scan.calls:
                extra = acquires.get(callee, set()) - acquires[name]
                if extra:
                    acquires[name] |= extra
                    changed = True
    edges = {}   # (src, dst) -> (lineno, col, method)
    for name, scan in scans.items():
        for lock, heldt, lineno, col in scan.acquisitions:
            for src in heldt:
                if src != lock and (src, lock) not in edges:
                    edges[(src, lock)] = (lineno, col, name)
        for callee, heldt, lineno, col in scan.calls:
            for lock in acquires.get(callee, ()):
                for src in heldt:
                    if src != lock and (src, lock) not in edges:
                        edges[(src, lock)] = (lineno, col, name)
    adj = {}
    for (src, dst) in edges:
        adj.setdefault(src, set()).add(dst)
    reported = set()
    for (src, dst), (lineno, col, method) in sorted(
            edges.items(), key=lambda kv: (kv[1][0], kv[1][1])):
        back = _bfs_path(adj, dst, src)
        if back is None:
            continue
        cycle_key = frozenset(back)
        if cycle_key in reported:
            continue
        reported.add(cycle_key)
        bl, bc, bm = edges[(back[0], back[1])]
        findings.append(Finding(
            path, lineno, col, "CD1102",
            "lock-order inversion in %s: %s takes self.%s -> self.%s "
            "here, but %s takes %s (line %d) — two threads running "
            "these paths deadlock"
            % (cls.name, method, src, dst, bm,
               " -> ".join("self.%s" % n for n in back), bl)))

    # ---- CD1101: guarded fields accessed unlocked on thread paths ------
    if not info.entry_methods:
        return
    # methods reachable from entry points via self-calls
    reach = set(info.entry_methods)
    frontier = list(reach)
    while frontier:
        m = frontier.pop()
        for callee, _h, _l, _c in scans[m].calls:
            if callee not in reach and callee in scans:
                reach.add(callee)
                frontier.append(callee)
    locked_n = {}
    unlocked = {}   # field -> [(method, lineno, col)]
    total_n = {}
    for name, scan in scans.items():
        init = name == "__init__"
        for field, under, lineno, col, _store in scan.accesses:
            if init:
                continue
            total_n[field] = total_n.get(field, 0) + 1
            if under:
                locked_n[field] = locked_n.get(field, 0) + 1
            else:
                unlocked.setdefault(field, []).append(
                    (name, lineno, col))
    for field, n_locked in locked_n.items():
        outside = unlocked.get(field, ())
        if n_locked < 2 or not outside or n_locked <= len(outside):
            continue                       # not predominantly guarded
        for method, lineno, col in outside:
            if method not in reach:
                continue
            findings.append(Finding(
                path, lineno, col, "CD1101",
                "self.%s is guarded (%d of %d accesses hold a lock) "
                "but this thread-reachable access in %s.%s holds none "
                "— a racing writer can interleave; take the lock or "
                "copy the value out under it"
                % (field, n_locked, total_n[field], cls.name, method)))


def _bfs_path(adj, src, dst):
    frontier = [[src]]
    seen = {src}
    while frontier:
        p = frontier.pop(0)
        for nxt in adj.get(p[-1], ()):
            if nxt == dst:
                return p + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(p + [nxt])
    return None


def run(path, tree, findings=None):
    """Run the CD pass over one parsed module; returns the findings."""
    if findings is None:
        findings = []
    lock_attrs = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _check_class(path, node, findings)
            info = _collect_class(node)
            lock_attrs.update(info.locks)
    _Cd1104Checker(path, findings, lock_attrs).run(tree)
    return findings
