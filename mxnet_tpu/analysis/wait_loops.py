"""RB7xx — robustness: unbounded condition-wait loops.

``RB701`` flags the silent-hang shape that wedged the distributed tier
(the pre-fault-tolerance ``DistServer._do_push``/``_do_barrier``):

.. code-block:: python

    while not predicate:
        cv.wait(timeout=60)      # return value ignored, loop unbounded

The ``timeout=`` looks like a safety net but isn't one: ``wait`` returns
``False`` on timeout, the loop ignores it and re-waits, so a peer that
died turns into an *infinite* re-check loop with zero diagnostics.  The
fix is a real deadline — compute ``remaining = deadline - monotonic()``
each pass and raise (naming what's missing) when it runs out.

Heuristic: an expression-statement ``<obj>.wait(timeout=...)`` inside a
``while`` body is flagged UNLESS the loop shows deadline evidence —
a call to ``time.monotonic``/``time.time``/``perf_counter`` anywhere in
the loop, or an identifier mentioning ``deadline``/``remaining``.
A ``wait`` whose result is consumed (``if not cv.wait(...)``,
``ok = cv.wait(...)``) is not an Expr statement and never matches.
"""
from __future__ import annotations

import ast

from .findings import Finding

_CLOCK_FUNCS = frozenset({"monotonic", "time", "perf_counter",
                          "monotonic_ns", "time_ns", "perf_counter_ns"})
_DEADLINE_WORDS = ("deadline", "remaining", "time_left", "timeleft")


def _has_deadline_evidence(loop):
    """True if the while-loop's subtree (test included) computes or
    consults a deadline."""
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                (fn.id if isinstance(fn, ast.Name) else "")
            if name in _CLOCK_FUNCS:
                return True
        elif isinstance(node, ast.Name):
            low = node.id.lower()
            if any(w in low for w in _DEADLINE_WORDS):
                return True
        elif isinstance(node, ast.Attribute):
            low = node.attr.lower()
            if any(w in low for w in _DEADLINE_WORDS):
                return True
    return False


def _is_ignored_timed_wait(stmt):
    """Expr-statement ``<obj>.wait(timeout=...)`` (result discarded)."""
    if not isinstance(stmt, ast.Expr) or not isinstance(stmt.value, ast.Call):
        return False
    call = stmt.value
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "wait"):
        return False
    return any(kw.arg == "timeout" for kw in call.keywords) or call.args


class _WaitLoopChecker(ast.NodeVisitor):
    def __init__(self, path, findings):
        self.path = path
        self.findings = findings

    def visit_While(self, node):
        if not _has_deadline_evidence(node):
            for stmt in ast.walk(node):
                if _is_ignored_timed_wait(stmt):
                    self.findings.append(Finding(
                        self.path, stmt.lineno,
                        getattr(stmt, "col_offset", 0), "RB701",
                        ".wait(timeout=...) return value ignored in a "
                        "re-check loop with no deadline: a dead peer "
                        "re-waits forever with zero diagnostics — track "
                        "`remaining = deadline - monotonic()` and raise "
                        "(naming what is missing) when it expires"))
        self.generic_visit(node)


def run(path, tree, findings=None):
    """Run the RB pass over one parsed module; returns the findings list."""
    if findings is None:
        findings = []
    _WaitLoopChecker(path, findings).visit(tree)
    return findings
