"""TS1xx — tracing-safety lint over traceable function bodies.

Targets, found purely syntactically so the pass runs on un-importable
sources:

* every ``def hybrid_forward(self, F, ...)`` (the ``HybridBlock`` contract:
  tensor inputs are everything after ``F``, plus ``*args``/``**params``);
* functions decorated with ``jit`` / ``jax.jit`` / ``partial(jax.jit, ...)``
  (all parameters treated as traced);
* module-level functions passed by name to ``hybridize(...)`` or
  ``jax.jit(...)`` anywhere in the same module.

The pass runs a small intraprocedural taint analysis: tensor parameters
seed the tainted set; taint flows through arithmetic, comparisons,
subscripts, method calls on tainted receivers and ``F.*`` op calls, and
stops at host metadata (``.shape``/``.dtype``/``.ndim``/``.size``/
``len()``) and ``is None`` checks — that is exactly the boundary between
"graph value" and "Python value" that XLA tracing enforces at runtime.
Over-taint produces false positives (suppressible), under-taint misses
bugs; the metadata stops above keep the framework's own 100+
``hybrid_forward`` bodies clean without suppressions.
"""
from __future__ import annotations

import ast

from .findings import Finding

# attributes of an array that live on the HOST (reading them under trace is
# free and yields plain Python values)
_METADATA_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "context", "ctx", "stype", "name",
})

# calls that launder taint into host values we intentionally don't chase
_HOST_BUILTINS = frozenset({
    "len", "isinstance", "issubclass", "getattr", "hasattr", "type", "str",
    "repr", "range", "enumerate", "zip", "list", "tuple", "dict", "set",
    "sorted", "reversed", "print", "format", "id", "callable", "min", "max",
})

# builtin coercions that force a concrete value out of a tracer (TS103)
_COERCIONS = frozenset({"float", "int", "bool", "complex"})

# method names that force a device->host sync (TS103)
_SYNC_METHODS = frozenset({"asnumpy", "asscalar", "item", "tolist",
                           "wait_to_read"})


def _decorator_is_jit(dec):
    """True for @jit, @jax.jit, @partial(jax.jit, ...), @functools.partial(
    jax.jit, ...)."""
    if isinstance(dec, ast.Call):
        fname = _dotted(dec.func)
        if fname in ("partial", "functools.partial") and dec.args:
            return _dotted(dec.args[0]) in ("jit", "jax.jit")
        return _dotted(dec.func) in ("jit", "jax.jit")
    return _dotted(dec) in ("jit", "jax.jit")


def _dotted(node):
    """'a.b.c' for Name/Attribute chains, else ''. """
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _jit_call_targets(tree):
    """Names of module-level functions passed to hybridize()/jax.jit()."""
    targets = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        if fname.endswith("hybridize") or fname in ("jit", "jax.jit"):
            for a in node.args:
                if isinstance(a, ast.Name):
                    targets.add(a.id)
    return targets


def collect_traced_functions(tree):
    """Yield (funcdef, f_param_name_or_None, traced_param_names)."""
    jit_targets = _jit_call_targets(tree)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = node.args
        names = [a.arg for a in args.args]
        if node.name == "hybrid_forward" and len(names) >= 2:
            f_param = names[1]
            traced = set(names[2:])
            if args.vararg:
                traced.add(args.vararg.arg)
            if args.kwarg:
                traced.add(args.kwarg.arg)
            traced.update(a.arg for a in args.kwonlyargs)
            yield node, f_param, traced
        elif (any(_decorator_is_jit(d) for d in node.decorator_list)
              or node.name in jit_targets):
            traced = {n for n in names if n != "self"}
            if args.vararg:
                traced.add(args.vararg.arg)
            if args.kwarg:
                traced.add(args.kwarg.arg)
            traced.update(a.arg for a in args.kwonlyargs)
            yield node, None, traced


class _TaintChecker(ast.NodeVisitor):
    """One traceable function body; records TS findings."""

    def __init__(self, path, f_param, tainted, registry_names, findings):
        self.path = path
        self.f_param = f_param
        self.tainted = set(tainted)
        self.registry_names = registry_names  # None disables TS105
        self.findings = findings

    # -- taint query ------------------------------------------------------
    def is_tainted(self, node):
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _METADATA_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` are presence checks on the
            # PYTHON reference, legal under tracing
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return (self.is_tainted(node.left)
                    or any(self.is_tainted(c) for c in node.comparators))
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name):
                if fn.id in _HOST_BUILTINS or fn.id in _COERCIONS:
                    return False
                # helper(x): assume array-in, array-out
                return any(self.is_tainted(a) for a in node.args)
            if isinstance(fn, ast.Attribute):
                if fn.attr in _SYNC_METHODS:
                    return False  # result is a host value (and flagged)
                if (isinstance(fn.value, ast.Name)
                        and fn.value.id == self.f_param):
                    return True  # F.op(...) produces a traced array
                if self.is_tainted(fn.value):
                    return True  # x.reshape(...), self.proj(x)...
                return any(self.is_tainted(a) for a in node.args)
        return False

    def _flag(self, node, rule, message):
        self.findings.append(Finding(self.path, node.lineno,
                                     getattr(node, "col_offset", 0),
                                     rule, message))

    # -- statements -------------------------------------------------------
    def visit_Assign(self, node):
        if self.is_tainted(node.value):
            for tgt in node.targets:
                self._taint_target(tgt)
        else:
            for tgt in node.targets:
                self._untaint_target(tgt)
        self._check_mutation_targets(node.targets)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None and self.is_tainted(node.value):
            self._taint_target(node.target)
        self._check_mutation_targets([node.target])
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name) and self.is_tainted(node.value):
            self.tainted.add(node.target.id)
        self._check_mutation_targets([node.target])
        self.generic_visit(node)

    def _taint_target(self, tgt):
        if isinstance(tgt, ast.Name):
            self.tainted.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._taint_target(e)
        elif isinstance(tgt, ast.Starred):
            self._taint_target(tgt.value)

    def _untaint_target(self, tgt):
        if isinstance(tgt, ast.Name):
            self.tainted.discard(tgt.id)

    def _check_mutation_targets(self, targets):
        for tgt in targets:
            if (isinstance(tgt, ast.Subscript)
                    and self.is_tainted(tgt.value)):
                self._flag(tgt, "TS104",
                           "in-place subscript store into traced array "
                           "%r; use functional updates "
                           "(e.g. F.where / concat)" % _dotted(tgt.value))

    def visit_If(self, node):
        if self.is_tainted(node.test):
            self._flag(node.test, "TS101",
                       "branch condition depends on a traced array value; "
                       "use F.where or hoist the decision out of the "
                       "traced region")
        self.generic_visit(node)

    def visit_While(self, node):
        if self.is_tainted(node.test):
            self._flag(node.test, "TS102",
                       "loop condition depends on a traced array value; "
                       "use a static trip count or F.contrib.while_loop")
        self.generic_visit(node)

    def visit_Assert(self, node):
        if self.is_tainted(node.test):
            self._flag(node.test, "TS101",
                       "assert on a traced array value forces "
                       "concretization mid-trace")
        self.generic_visit(node)

    # -- expressions ------------------------------------------------------
    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in _SYNC_METHODS and self.is_tainted(fn.value):
                self._flag(node, "TS103",
                           ".%s() on a traced array syncs device->host "
                           "mid-trace" % fn.attr)
            elif (isinstance(fn.value, ast.Name)
                  and fn.value.id == self.f_param
                  and self.registry_names is not None
                  and fn.attr not in self.registry_names):
                self._flag(node, "TS105",
                           "%s.%s is not a registered op (ops.registry "
                           "_REGISTRY/_ALIASES)" % (fn.value.id, fn.attr))
        elif isinstance(fn, ast.Name) and fn.id in _COERCIONS:
            if any(self.is_tainted(a) for a in node.args):
                self._flag(node, "TS103",
                           "%s() on a traced array concretizes it "
                           "mid-trace" % fn.id)
        self.generic_visit(node)

    # nested defs get their own traced-function treatment only if they
    # qualify; inside a traced body a nested def shares the tainted env
    def visit_FunctionDef(self, node):
        self.generic_visit(node)


def check_function(path, funcdef, f_param, traced_params, registry_names,
                   findings):
    checker = _TaintChecker(path, f_param, traced_params, registry_names,
                            findings)
    for stmt in funcdef.body:
        checker.visit(stmt)


def run(path, tree, registry_names=None, findings=None):
    """Run the TS pass over one parsed module; returns the findings list."""
    if findings is None:
        findings = []
    for funcdef, f_param, traced in collect_traced_functions(tree):
        check_function(path, funcdef, f_param, traced, registry_names,
                       findings)
    return findings
