"""GS5xx — per-node graph verification for Symbol DAGs.

The reference validated graphs through nnvm's ``InferShape``/``InferType``
passes, which attribute a failure to the offending node; the rebuild's
whole-graph ``jax.eval_shape`` instead surfaces one opaque traceback with
no node attribution.  This pass restores the per-node story: an abstract
interpreter walks ``Symbol._topo_nodes()`` in topo order, propagating
``jax.ShapeDtypeStruct``s node by node (reusing ``ops.registry`` metadata
and ``symbol/shape_hints.py`` to fill parameter shapes), so a mismatch is
blamed on exactly one node with its input shapes and producing nodes.

Rules (catalogue in ``findings.RULES``):

* ``GS501`` — a node's shape/dtype check failed (or its op is not
  registered, or it produced a different output count than declared)
* ``GS502`` — an input variable's shape is unresolvable; the finding
  names the FIRST consumer node that needed it
* ``GS503`` — duplicate node names (name-keyed bindings silently alias)
* ``GS504`` — a supplied argument binding matches no graph input
* ``GS505`` — a join node mixes float inputs of different widths

Entry points: :func:`verify_symbol` (programmatic), ``Symbol.lint()``
(method sugar), the ``MXNET_GRAPH_VERIFY=1`` pre-flight in
``bind``/``simple_bind``, and ``tools/mxlint.py <file>.json`` for
serialized graphs.  Findings use pseudo-paths ``symbol:<name>`` (or the
file path for ``.json`` inputs); the ``line`` is the node's 1-based topo
position, which is stable for a given graph.
"""
from __future__ import annotations

import numpy as _np

from .findings import Finding


def _first_line(exc):
    s = str(exc).strip()
    return s.splitlines()[0] if s else type(exc).__name__


def _slot_names(node):
    """Input slot names for an op node (registry order), falling back to
    positional ``arg<i>`` labels for variadic/unknown ops."""
    try:
        from ..ops import registry as _reg

        reg = _reg.get(node.op)
        if not reg.variadic and len(reg.input_names) >= len(node.inputs):
            return list(reg.input_names[:len(node.inputs)])
    except Exception:
        pass
    return ["arg%d" % i for i in range(len(node.inputs))]


def input_consumers(sym):
    """Map variable name -> [(consumer_node, slot_name)] in topo order.

    The shared blame helper: both GS502 and the enriched
    ``infer_shape: cannot infer ...`` error path use it to answer
    "which node needed this input".
    """
    out = {}
    for node in sym._topo_nodes():
        if node.is_variable:
            continue
        for slot, (inp, _idx) in zip(_slot_names(node), node.inputs):
            if inp.is_variable:
                out.setdefault(inp.name, []).append((node, slot))
    return out


def blame_unresolved(sym, missing):
    """Human-readable blame for unresolved inputs: each name annotated
    with the first consumer node that needed it."""
    consumers = input_consumers(sym)
    parts = []
    for name in missing:
        uses = consumers.get(name)
        if uses:
            node, slot = uses[0]
            parts.append("%r (first needed by node %r (%s) as input %r)"
                         % (name, node.name, node.op, slot))
        else:
            parts.append("%r (never consumed by any op node)" % (name,))
    return ", ".join(parts)


def _var_dtype(node, arg_dtypes):
    dt = arg_dtypes.get(node.name)
    if dt is None:
        dt = node.attrs.get("__dtype__")
    return _np.dtype(dt) if dt is not None else _np.dtype(_np.float32)


def verify_symbol(sym, arg_shapes=None, arg_dtypes=None, path=None):
    """Run the GS5xx checks over one Symbol; returns a list of Findings.

    ``arg_shapes``/``arg_dtypes`` (name->shape / name->dtype) seed the
    propagation on top of the ``shape=``/``dtype=`` attrs attached at
    ``var()`` creation; ``shape_hints`` fills parameter shapes the same
    way ``infer_shape`` does, so a graph that binds cleanly lints
    cleanly with only its data shapes supplied.
    """
    import jax

    from ..ops import registry as _reg
    from ..symbol import shape_hints
    from ..symbol.symbol import _op_attrs

    findings = []
    if path is None:
        path = "symbol:%s" % (sym.name or "group%d" % len(sym._outputs))
    arg_shapes = dict(arg_shapes or {})
    arg_dtypes = dict(arg_dtypes or {})
    nodes = sym._topo_nodes()
    topo_line = {id(n): i + 1 for i, n in enumerate(nodes)}

    def flag(node, rule, message):
        findings.append(Finding(path, topo_line[id(node)], 0, rule, message))

    # -- GS503: duplicate node names --------------------------------------
    seen = {}
    for node in nodes:
        prev = seen.get(node.name)
        if prev is not None:
            flag(node, "GS503",
                 "duplicate node name %r: this %s node collides with the "
                 "%s node at topo position %d — name-keyed bindings and "
                 "serialization silently alias one of them"
                 % (node.name, node.op or "variable",
                    prev.op or "variable", topo_line[id(prev)]))
        else:
            seen[node.name] = node

    # -- GS504: supplied bindings that match no graph input ----------------
    graph_inputs = set(sym.list_inputs())
    for name in sorted(set(arg_shapes) | set(arg_dtypes)):
        if name not in graph_inputs:
            shown = sorted(graph_inputs)
            if len(shown) > 8:
                shown = shown[:8] + ["..."]
            findings.append(Finding(path, 0, 0, "GS504",
                                    "argument %r matches no graph input "
                                    "(inputs: %s) — binding would silently "
                                    "drop it" % (name, shown)))

    # -- per-node abstract interpretation ---------------------------------
    vals = {}          # id(node) -> tuple of ShapeDtypeStruct|None per output
    unresolved = {}    # var name -> (var_node, consumer_node, slot)

    for node in nodes:
        if node.is_variable:
            shp = arg_shapes.get(node.name)
            if shp is None and "__shape__" in node.attrs:
                s = tuple(node.attrs["__shape__"])
                if all(d != 0 for d in s):
                    shp = s
            if shp is None:
                vals[id(node)] = (None,)
            else:
                vals[id(node)] = (jax.ShapeDtypeStruct(
                    tuple(shp), _var_dtype(node, arg_dtypes)),)
            continue

        n_out = max(1, node.num_outputs)
        try:
            reg = _reg.get(node.op)
        except Exception as e:
            flag(node, "GS501", "node %r: %s" % (node.name, _first_line(e)))
            vals[id(node)] = (None,) * n_out
            continue

        entries = node.inputs
        ins = [vals[id(inp)][idx] for inp, idx in entries]

        # fill missing variable inputs from the op's shape hint (the same
        # backwards solving infer_shape uses)
        if any(s is None for s in ins):
            shapes_in = [None if s is None else tuple(s.shape) for s in ins]
            try:
                hinted = shape_hints.hint(node.op, reg.input_names,
                                          shapes_in, node.attrs)
            except Exception:
                hinted = None
            if hinted:
                for i, ((inp, _idx), s) in enumerate(zip(entries, hinted)):
                    if s is not None and ins[i] is None and inp.is_variable:
                        vals[id(inp)] = (jax.ShapeDtypeStruct(
                            tuple(s), _var_dtype(inp, arg_dtypes)),)
            ins = [vals[id(inp)][idx] for inp, idx in entries]

        if any(s is None for s in ins):
            # variables still unknown get GS502 (blamed on their first
            # consumer); a None from a FAILED producer node is a cascade —
            # stay silent, the producer already carries the finding
            for slot, ((inp, _idx), s) in zip(_slot_names(node),
                                              zip(entries, ins)):
                if s is None and inp.is_variable \
                        and inp.name not in unresolved:
                    unresolved[inp.name] = (inp, node, slot)
            vals[id(node)] = (None,) * n_out
            continue

        # -- GS505: mixed float widths at a join ---------------------------
        # cast-type ops (Cast, amp_cast, amp_multicast — NOT broadcast_*,
        # whose "cast" is a substring accident) exist to mix dtypes
        is_cast = "cast" in node.op.lower().split("_")
        if len(ins) >= 2 and not is_cast:
            widths = sorted({str(s.dtype) for s in ins
                             if _np.dtype(s.dtype).kind == "f"})
            if len(widths) > 1:
                flag(node, "GS505",
                     "node %r (%s) joins inputs of mixed float dtypes %s "
                     "(from %s) — silent promotion to the widest; cast "
                     "explicitly if intended"
                     % (node.name, node.op, widths,
                        ["%s[%d]" % (inp.name, idx)
                         for inp, idx in entries]))

        # -- GS501: per-node abstract evaluation ---------------------------
        attrs = _op_attrs(node, "predict" if reg.needs_mode else None)

        def one(*arrs, _reg_=reg, _attrs_=attrs):
            a = list(arrs)
            if _reg_.needs_rng:
                a = [jax.random.PRNGKey(0)] + a
            out = _reg_.forward(*a, **_attrs_)
            return out if isinstance(out, tuple) else (out,)

        try:
            outs = jax.eval_shape(one, *ins)
        except Exception as e:
            flag(node, "GS501",
                 "node %r (op %s): shape/dtype check failed for input "
                 "shapes %s (inputs: %s): %s"
                 % (node.name, node.op,
                    [tuple(s.shape) for s in ins],
                    ["%s[%d]" % (inp.name, idx) for inp, idx in entries],
                    _first_line(e)))
            vals[id(node)] = (None,) * n_out
            continue
        if len(outs) != node.num_outputs:
            flag(node, "GS501",
                 "node %r (op %s) declares %d outputs but its forward "
                 "produced %d under abstract evaluation"
                 % (node.name, node.op, node.num_outputs, len(outs)))
        vals[id(node)] = tuple(outs) + (None,) * max(
            0, node.num_outputs - len(outs))

    # -- GS502: unresolved inputs, blamed on their first consumer ----------
    for name, (var_node, consumer, slot) in unresolved.items():
        flag(var_node, "GS502",
             "cannot infer shape of input %r — first needed by node %r "
             "(%s) as input %r; pass its shape to lint()/infer_shape or "
             "attach shape= at var()"
             % (name, consumer.name, consumer.op, slot))

    findings.sort(key=lambda f: (f.line, f.rule))
    return findings
