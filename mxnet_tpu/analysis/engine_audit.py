"""EA4xx — runtime engine dependency auditor (``MXNET_ENGINE_AUDIT=1``).

The TPU engine (``engine.py``) keeps MXNet's versioned-variable contract:
every mutation of engine-visible state flows through ``Engine.push`` with a
declared ``write_vars`` set, and ``push`` is the only caller of
``Var.on_write``.  The whole "async engine collapses onto XLA enqueue
order" argument rests on that contract — state changing outside a declared
write set is invisible to the executable caches keyed on versions, and is
precisely the class of reference bugs the registry docstring claims is
"gone by design".  This auditor makes the claim checkable:

* ``EA401`` *out-of-band write* — a var arrives at ``push`` with a version
  different from the one the engine last published for it: something wrote
  it while skipping ``Var.on_write``/the declared write set (or bumped it
  by hand and never declared the write).
* ``EA402`` *overlapping concurrent writes* — two threads are inside
  ``push`` simultaneously with intersecting write sets; enqueue order no
  longer determines the final version.
* ``EA403`` *version regression* — a var's version moved backwards; state
  was rolled back behind the engine's back.

Enable with ``MXNET_ENGINE_AUDIT=1`` (checked at Engine construction), or
programmatically::

    from mxnet_tpu.analysis import install, uninstall
    audit = install()           # raises EngineAuditError on violation
    audit = install(strict=False)   # collect into audit.violations
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from ..testing import lockcheck as _lockcheck
from .findings import rule_doc


class EngineAuditError(MXNetError):
    """A declared read/write var set was violated (see rule EA4xx)."""

    def __init__(self, rule, message):
        super().__init__("%s — %s" % (message, rule_doc(rule)))
        self.rule = rule


class EngineAudit:
    """Validates var sets at every ``Engine.push``; see module docstring.

    The engine calls ``before_push``/``after_push`` around the op body when
    an audit is installed (``Engine._audit``).  Thread-safe: the writing-set
    table is the whole point of EA402.
    """

    def __init__(self, strict=True):
        self.strict = strict
        self.violations = []  # (rule, message) when strict=False
        self._lock = _lockcheck.named_lock("engine.audit")
        self._published = {}  # vid -> version as last seen by the engine
        self._writing = {}    # vid -> thread ident currently writing it
        self.checked_pushes = 0

    def _violate(self, rule, message):
        if self.strict:
            raise EngineAuditError(rule, message)
        self.violations.append((rule, message))

    def before_push(self, read_vars, write_vars, op_name):
        me = threading.get_ident()
        name = op_name or "<op>"
        with self._lock:
            self.checked_pushes += 1
            for v in tuple(read_vars) + tuple(write_vars):
                last = self._published.get(v.vid)
                if last is None:
                    self._published[v.vid] = v.version
                elif v.version < last:
                    self._violate(
                        "EA403",
                        "var #%d at version %d but engine last published "
                        "%d (push of %s)" % (v.vid, v.version, last, name))
                elif v.version != last:
                    self._violate(
                        "EA401",
                        "var #%d at version %d but engine last published "
                        "%d: it was written outside a declared write set "
                        "(push of %s)" % (v.vid, v.version, last, name))
            for v in write_vars:
                owner = self._writing.get(v.vid)
                if owner is not None and owner != me:
                    self._violate(
                        "EA402",
                        "var #%d is in the write set of two concurrent "
                        "pushes (threads %d and %d; push of %s)"
                        % (v.vid, owner, me, name))
                else:
                    self._writing[v.vid] = me

    def after_push(self, read_vars, write_vars, op_name):
        me = threading.get_ident()
        with self._lock:
            for v in write_vars:
                if self._writing.get(v.vid) == me:
                    del self._writing[v.vid]
            # publish post-push versions (push bumped the write vars)
            for v in tuple(read_vars) + tuple(write_vars):
                self._published[v.vid] = v.version


def install(engine=None, strict=True):
    """Attach a fresh ``EngineAudit`` to the engine; returns it."""
    if engine is None:
        from ..engine import Engine
        engine = Engine.get()
    audit = EngineAudit(strict=strict)
    engine._audit = audit
    return audit


def uninstall(engine=None):
    if engine is None:
        from ..engine import Engine
        engine = Engine.get()
    engine._audit = None
