"""mxlint pass 12: ownership & lifecycle discipline (RL12xx).

The engine's design makes every expensive thing a *handle* whose
release is someone else's job — arena pages freed by the scheduler,
sockets evicted by the kvstore client, request futures resolved by the
serve loop, temp dirs removed by the bench harness.  A handle that
leaks on one early-exit path is invisible in every test that takes the
happy path, and at fleet scale the leak IS the outage.  This pass
tracks acquire/release pairs path-sensitively through each function
body, over the repo's real handle kinds:

==========  =====================================  =======================
kind        acquired by                            released by
==========  =====================================  =======================
arena       ``<x>.alloc(...)``                     ``<x>.free(h, ...)``
socket      ``socket.socket()`` /                  ``h.close()``
            ``socket.create_connection()``
tempfile    ``tempfile.mkdtemp()``                 ``shutil.rmtree(h)``,
                                                   ``os.remove/unlink/
                                                   rmdir(h)``
future      ``Request(...)`` / ``Future()``        ``h.set_result/
                                                   set_exception/
                                                   cancel(...)``
thread      ``threading.Thread(...)``              ``h.join(...)``
            (non-daemon, bound to a local)
==========  =====================================  =======================

Rules:

* **RL1201** (error) — a reachable ``return``/``raise``/fall-through
  exits the function with a handle neither released nor handed off.
* **RL1202** (error) — an OS resource (socket, temp dir) is *used*
  before its cleanup is registered: any statement between the acquire
  and the protecting ``try`` can raise, and the handle leaks.  The fix
  is mechanical — the ``try`` whose ``finally`` (or close-and-reraise
  ``except``) releases the handle must start on the line after the
  acquire.
* **RL1203** (warn) — a Request/Future has a reachable path that
  neither resolves nor cancels it and never hands it off: a waiter on
  that path hangs forever.
* **RL1204** (error) — double release, or any use after release,
  along one path.
* **RL1205** (warn) — a bare/broad ``except: pass`` inside a cleanup
  scope (a ``finally`` block, a try whose body releases something, or
  a close/stop/drain-shaped function): a failed release is silently
  indistinguishable from a successful one.

Like every pass the analysis is conservative: handles are believed
only when literally visible (a direct ``name = <acquire-call>``
binding), handing a handle to any call or storing it anywhere
transfers ownership and ends tracking, and ``with``-managed acquires
are never tracked (the context manager is the cleanup registration).
The dynamic half is ``MXNET_RESCHECK=1`` (``testing/rescheck.py``): a
tracked-handle registry over the same kinds that reports live handles
at ``drain()``/``stop()``/atexit as ``ResourceLeakError`` with
creation stacks — see ``docs/static_analysis.md`` Pass 12.
"""
from __future__ import annotations

import ast
import re

from .findings import Finding

__all__ = ["run"]

# kinds with OS-level cost where an unprotected raise window is itself
# an error (RL1202); the others get leak/double-free tracking only
_OS_KINDS = frozenset({"socket", "tempfile"})

_KIND_NOUN = {
    "arena": "arena pages",
    "socket": "socket",
    "tempfile": "temp file/dir",
    "future": "future",
    "thread": "thread",
}

_FUTURE_CTORS = frozenset({"Request", "Future"})
_TEMPFILE_RELEASERS = frozenset({"rmtree", "remove", "unlink", "rmdir"})
_FUTURE_RESOLVERS = frozenset({"set_result", "set_exception", "cancel"})

_CLEANUP_NAME = re.compile(
    r"(^|_)(close|stop|drain|shutdown|release|free|evict|cleanup|"
    r"uninstall|terminate|teardown|atexit)($|_)|^__(exit|del)__$")

# an `.attr(...)` call whose presence marks a try body as cleanup code
_RELEASE_ATTRS = frozenset({
    "close", "rmtree", "remove", "unlink", "rmdir", "terminate", "kill",
    "shutdown", "cancel", "release", "free", "disarm",
})


def run(path, tree, findings=None):
    """Append RL12xx findings for ``tree`` to ``findings``."""
    findings = findings if findings is not None else []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FnScan(path, findings).scan(node)
            _scan_swallows(path, node, findings)
    return findings


# ---------------------------------------------------------------------------
# acquire / release vocabulary
# ---------------------------------------------------------------------------
def _acquire_kind(call):
    """Handle kind a ``name = <call>`` binding acquires, or None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        base = f.value
        if isinstance(base, ast.Name):
            if base.id == "socket" and f.attr in ("socket",
                                                  "create_connection"):
                return "socket"
            if base.id == "tempfile" and f.attr == "mkdtemp":
                return "tempfile"
            if base.id == "threading" and f.attr == "Thread":
                return _thread_kind(call)
        if f.attr == "alloc":
            return "arena"
        if f.attr in _FUTURE_CTORS:
            return "future"
    elif isinstance(f, ast.Name):
        if f.id in _FUTURE_CTORS:
            return "future"
        if f.id == "Thread":
            return _thread_kind(call)
    return None


def _thread_kind(call):
    """Daemon threads are fire-and-forget by declaration: untracked."""
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and kw.value.value:
            return None
    return "thread"


def _release_target(call, env):
    """Name of the tracked handle ``call`` releases/resolves, or None."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    recv = f.value
    if isinstance(recv, ast.Name) and recv.id in env:
        kind = env[recv.id][0].kind
        if f.attr == "close" and kind in ("socket", "tempfile"):
            return recv.id
        if f.attr == "join" and kind == "thread":
            return recv.id
        if f.attr in _FUTURE_RESOLVERS and kind == "future":
            return recv.id
    if isinstance(recv, ast.Name) and recv.id in ("shutil", "os") \
            and f.attr in _TEMPFILE_RELEASERS and call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Name) and a0.id in env \
                and env[a0.id][0].kind == "tempfile":
            return a0.id
    if f.attr == "free" and call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Name) and a0.id in env \
                and env[a0.id][0].kind == "arena":
            return a0.id
    return None


def _releases_name(try_node, name):
    """True when ``try_node``'s finally (or any except handler) contains
    a release-shaped call on ``name`` — the handle is *protected*: every
    path out of the try runs the cleanup (finally), or the failure path
    closes and re-raises (the cache-on-success idiom)."""
    blocks = list(try_node.finalbody)
    for h in try_node.handlers:
        blocks.extend(h.body)
    for st in blocks:
        for node in ast.walk(st):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            f = node.func
            recv = f.value
            if isinstance(recv, ast.Name) and recv.id == name \
                    and f.attr in _RELEASE_ATTRS | _FUTURE_RESOLVERS \
                    | {"join"}:
                return True
            if f.attr in _RELEASE_ATTRS | {"free"} and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id == name:
                return True
    return False


def _none_narrow(test):
    """``(name, branch)`` when ``test`` is a None/falsy check on a bare
    name: ``branch`` is the side on which the name is None/falsy
    (``"body"`` for ``h is None`` / ``not h``, ``"orelse"`` for
    ``h is not None`` / bare ``h``)."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.left, ast.Name) \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return test.left.id, "body"
        if isinstance(test.ops[0], ast.IsNot):
            return test.left.id, "orelse"
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Name):
        return test.operand.id, "body"
    if isinstance(test, ast.Name):
        return test.id, "orelse"
    return None, None


# ---------------------------------------------------------------------------
# the path-sensitive walker
# ---------------------------------------------------------------------------
class _Meta:
    """One acquisition site (shared across forked paths for dedupe)."""

    __slots__ = ("name", "kind", "line", "col", "flagged")

    def __init__(self, name, kind, line, col):
        self.name = name
        self.kind = kind
        self.line = line
        self.col = col
        self.flagged = False


# env: {name: [meta, state, release_line]} with state "live"/"released"
_LIVE, _RELEASED = "live", "released"


def _fork(env):
    return {k: list(v) for k, v in env.items()}


def _merge(env, e1, e2):
    env.clear()
    for name in set(e1) | set(e2):
        a, b = e1.get(name), e2.get(name)
        if a is None or b is None:
            env[name] = a or b
        elif a[0] is not b[0]:
            continue  # rebound differently per branch: give up on it
        elif a[1] == _RELEASED and b[1] == _RELEASED:
            env[name] = a
        else:
            # released on one path only: treat as live (optimistic —
            # a later release is legitimate on the live path)
            env[name] = a if a[1] == _LIVE else b


class _FnScan:
    def __init__(self, path, findings):
        self.path = path
        self.findings = findings
        self._try_stack = []

    def _emit(self, line, col, rule, msg):
        self.findings.append(Finding(self.path, line, col, rule, msg))

    def scan(self, fn):
        env = {}
        self.walk(fn.body, env)
        self._exit_check(env, fn.body[-1].lineno if fn.body else fn.lineno,
                         "falls off the end of %s()" % fn.name)

    # -- statements -------------------------------------------------------
    def walk(self, stmts, env):
        for st in stmts:
            self.stmt(st, env)

    def stmt(self, st, env):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs are scanned as their own functions
        if isinstance(st, ast.Assign):
            self._assign(st, env)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            if st.value is not None:
                self.value(st.value, env, handoff=True)
        elif isinstance(st, ast.Expr):
            self.value(st.value, env, handoff=False)
        elif isinstance(st, ast.Return):
            if st.value is not None:
                self.value(st.value, env, handoff=True)
            self._exit_check(env, st.lineno, "returns at line %d"
                             % st.lineno)
        elif isinstance(st, ast.Raise):
            if st.exc is not None:
                self.value(st.exc, env, handoff=False)
            self._exit_check(env, st.lineno, "raises at line %d"
                             % st.lineno)
        elif isinstance(st, ast.If):
            self.value(st.test, env, handoff=False)
            e1, e2 = _fork(env), _fork(env)
            # `if h is None:` narrows: the handle was never acquired on
            # that branch (the alloc-returns-None-when-full idiom)
            name, none_branch = _none_narrow(st.test)
            if name is not None:
                (e1 if none_branch == "body" else e2).pop(name, None)
            self.walk(st.body, e1)
            self.walk(st.orelse, e2)
            _merge(env, e1, e2)
        elif isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
            test = st.test if isinstance(st, ast.While) else st.iter
            self.value(test, env, handoff=False)
            self.walk(st.body, env)
            self.walk(st.orelse, env)
        elif isinstance(st, ast.Try):
            self._try(st, env)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                # with-managed acquires are never tracked: the context
                # manager IS the cleanup registration
                if not (isinstance(item.context_expr, ast.Call)
                        and _acquire_kind(item.context_expr)):
                    self.value(item.context_expr, env, handoff=False)
            self.walk(st.body, env)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    env.pop(t.id, None)
                else:
                    self.value(t, env, handoff=False)
        elif isinstance(st, ast.Assert):
            self.value(st.test, env, handoff=False)
        elif isinstance(st, (ast.Global, ast.Nonlocal, ast.Pass,
                             ast.Break, ast.Continue, ast.Import,
                             ast.ImportFrom)):
            pass
        # anything else: no handle-relevant semantics

    def _assign(self, st, env):
        value = st.value
        kind = _acquire_kind(value) if isinstance(value, ast.Call) else None
        if kind is not None:
            # still scan the acquire call's own arguments for uses
            for a in value.args:
                self.value(a, env, handoff=True)
            for kw in value.keywords:
                self.value(kw.value, env, handoff=True)
        else:
            self.value(value, env, handoff=True)
        for target in st.targets:
            if isinstance(target, ast.Name):
                old = env.pop(target.id, None)
                if old is not None and old[1] == _LIVE \
                        and not old[0].flagged \
                        and old[0].kind != "future":
                    old[0].flagged = True
                    self._emit(old[0].line, old[0].col, "RL1201",
                               "%s acquired here is dropped by the "
                               "rebinding at line %d without being "
                               "released" % (_KIND_NOUN[old[0].kind],
                                             st.lineno))
            elif isinstance(target, (ast.Tuple, ast.List)):
                for el in target.elts:
                    if isinstance(el, ast.Name):
                        env.pop(el.id, None)
            else:
                self.value(target.value, env, handoff=False) \
                    if isinstance(target, (ast.Attribute, ast.Subscript)) \
                    else None
        if kind is not None and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            name = st.targets[0].id
            if not any(_releases_name(t, name) for t in self._try_stack):
                env[name] = [_Meta(name, kind, st.lineno, st.col_offset),
                             _LIVE, None]

    def _try(self, st, env):
        # entering a try that releases a held handle in its finally (or
        # a close-and-reraise handler) protects it: stop tracking
        for name in [n for n, e in env.items()
                     if e[1] == _LIVE and _releases_name(st, n)]:
            del env[name]
        pre = _fork(env)
        self._try_stack.append(st)
        try:
            self.walk(st.body, env)
            self.walk(st.orelse, env)
            for h in st.handlers:
                # handlers run with the *pre-try* state: the exception
                # may have fired before any acquire in the body
                henv = _fork(pre)
                self.walk(h.body, henv)
        finally:
            self._try_stack.pop()
        self.walk(st.finalbody, env)

    def _exit_check(self, env, line, how):
        for name, entry in list(env.items()):
            meta, state, _rel = entry
            if state != _LIVE or meta.flagged:
                continue
            meta.flagged = True
            if meta.kind == "future":
                self._emit(meta.line, meta.col, "RL1203",
                           "future %r is neither resolved nor cancelled "
                           "on the path that %s — a waiter hangs forever"
                           % (name, how))
            else:
                self._emit(meta.line, meta.col, "RL1201",
                           "%s %r is not released on the path that %s"
                           % (_KIND_NOUN[meta.kind], name, how))

    # -- expressions ------------------------------------------------------
    def value(self, node, env, handoff):
        """Scan an expression: releases, risky uses, escapes, UAR."""
        if node is None:
            return
        if isinstance(node, ast.Call):
            self._call(node, env)
        elif isinstance(node, ast.Name):
            # a bare read (compare, return, container) is never a
            # use-after-release — returning a closed socket or a
            # resolved future is normal; only *operational* uses
            # (call argument / receiver, see _use) flag RL1204
            if handoff and node.id in env:
                del env[node.id]  # ownership handed off: stop tracking
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for el in node.elts:
                self.value(el, env, handoff=True)
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                self.value(k, env, handoff=True)
            for v in node.values:
                self.value(v, env, handoff=True)
        elif isinstance(node, (ast.Lambda, ast.GeneratorExp, ast.ListComp,
                               ast.SetComp, ast.DictComp)):
            # closure capture / comprehension use: conservatively an
            # ownership handoff for every tracked name inside
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id in env:
                    del env[sub.id]
        elif isinstance(node, (ast.Compare, ast.BoolOp, ast.BinOp,
                               ast.UnaryOp, ast.JoinedStr,
                               ast.FormattedValue, ast.Subscript,
                               ast.Attribute, ast.Starred, ast.Await,
                               ast.IfExp, ast.NamedExpr, ast.Slice)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.expr, ast.Slice)):
                    self.value(child, env, handoff=False)
        # constants & misc: nothing to do

    def _call(self, call, env):
        released = _release_target(call, env)
        if released is not None:
            entry = env[released]
            # scan the *other* argument expressions too
            for a in call.args:
                if not (isinstance(a, ast.Name) and a.id == released):
                    self.value(a, env, handoff=True)
            if entry[1] == _RELEASED:
                self._emit(call.lineno, call.col_offset, "RL1204",
                           "%s %r released again here — already "
                           "released at line %d"
                           % (_KIND_NOUN[entry[0].kind], released,
                              entry[2]))
                del env[released]
            else:
                entry[1] = _RELEASED
                entry[2] = call.lineno
            return
        # receiver use: h.method(...)
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in env:
            self._use(f.value.id, env, call.lineno, call.col_offset,
                      receiver=True)
        else:
            self.value(f, env, handoff=False)
        for a in call.args:
            if isinstance(a, ast.Name) and a.id in env:
                self._use(a.id, env, a.lineno, a.col_offset)
            else:
                self.value(a, env, handoff=True)
        for kw in call.keywords:
            if isinstance(kw.value, ast.Name) and kw.value.id in env:
                self._use(kw.value.id, env, kw.value.lineno,
                          kw.value.col_offset)
            else:
                self.value(kw.value, env, handoff=True)

    def _use(self, name, env, line, col, receiver=False):
        """A tracked handle fed to a non-release call (or used as the
        receiver of one)."""
        entry = env[name]
        meta, state, _rel = entry
        if state == _RELEASED:
            self._uar(name, entry, line, col)
            return
        if meta.kind in _OS_KINDS:
            if not meta.flagged:
                meta.flagged = True
                self._emit(line, col, "RL1202",
                           "%s %r (acquired at line %d) is used before "
                           "its cleanup is registered — an exception "
                           "here leaks it; start the try/finally (or "
                           "close-and-reraise except) on the line after "
                           "the acquire" % (_KIND_NOUN[meta.kind], name,
                                            meta.line))
            del env[name]
        elif receiver:
            # h.method() on a future/thread/page-list is the normal way
            # to operate it (t.start(), fut.done()): keep tracking
            pass
        else:
            # handing an arena page list / future / thread to a call
            # transfers ownership: stop tracking
            del env[name]

    def _uar(self, name, entry, line, col):
        meta = entry[0]
        if not meta.flagged:
            meta.flagged = True
            self._emit(line, col, "RL1204",
                       "%s %r used here after its release at line %d"
                       % (_KIND_NOUN[meta.kind], name, entry[2]))


# ---------------------------------------------------------------------------
# RL1205: broad swallows inside cleanup scopes
# ---------------------------------------------------------------------------
def _broad_handler(handler):
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception",
                                                "BaseException"):
            return True
    return False


def _only_pass(body):
    return len(body) == 1 and isinstance(body[0], ast.Pass)


def _has_release_call(stmts):
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _RELEASE_ATTRS:
                return True
    return False


def _scan_swallows(path, fn, findings):
    in_cleanup_fn = bool(_CLEANUP_NAME.search(fn.name))

    def walk(stmts, in_cleanup):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.Try):
                scope = in_cleanup or _has_release_call(st.body)
                for h in st.handlers:
                    if scope and _broad_handler(h) and _only_pass(h.body):
                        findings.append(Finding(
                            path, h.lineno, h.col_offset, "RL1205",
                            "broad except swallows failures inside a "
                            "cleanup/release scope — a failed release "
                            "looks successful; catch the narrow OSError "
                            "or record the failure"))
                    walk(h.body, in_cleanup)
                walk(st.body, in_cleanup)
                walk(st.orelse, in_cleanup)
                walk(st.finalbody, True)
            else:
                for attr in ("body", "orelse"):
                    sub = getattr(st, attr, None)
                    if isinstance(sub, list):
                        walk(sub, in_cleanup)

    walk(fn.body, in_cleanup_fn)
