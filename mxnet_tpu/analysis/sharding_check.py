"""SH9xx — sharding-consistency checks (mxlint pass 9).

The GSPMD substrate (``mxnet_tpu/sharding/``) makes placement a
first-class value: ``PartitionSpec`` axis names must exist in the mesh
they bind to, and resharding moves real bytes over ICI/DCN.  Both
mistakes are invisible at the call site — a bad axis name surfaces as
an async XLA error far from the spec literal, and a reshard in a hot
loop silently serializes device traffic the way a host sync in a loop
serializes dispatch.  This pass catches the statically visible cases:

* ``SH901`` — a ``PartitionSpec``/``P`` literal names an axis that no
  mesh built in the same module defines.  Fires only in modules that
  build at least one statically-known mesh (``Mesh({...})``,
  ``make_mesh({...})``, ``global_mesh({...})``, or the raw
  ``Mesh(devs, ("a", "b"))`` spelling) — variables and runtime-shaped
  meshes are never guessed at, same conservatism as CC601.
* ``SH902`` — ``.reshard(...)``, ``nd.shard(...)`` or an *eager*
  ``with_sharding_constraint`` inside a ``for``/``while`` body:
  resharding is cross-device data movement; in a loop it is the new
  host-sync-in-loop.  Eager ``with_sharding_constraint`` counts
  because outside a trace it is a registry op producing a re-placed
  array per iteration; inside a traced body (``hybrid_forward``,
  ``@jit`` — recognized via ``tracing_safety``'s traced-function
  collector) it is a free compile-time annotation and stays clean.
  Hoist the placement out of the loop, or move the loop under the
  trace.

Runtime counterpart: ``MXNET_SHARDING_VERIFY=1``
(``sharding/verify.py``) pre-flights dynamically built spec/mesh pairs
the AST cannot see.
"""
from __future__ import annotations

import ast

from .findings import Finding
from .tracing_safety import _dotted

_MESH_BUILDERS = frozenset({"make_mesh", "global_mesh", "Mesh"})
_SPEC_NAMES = frozenset({"P", "PartitionSpec"})


def _dict_axes(node):
    """``{"data": 4, "model": -1}`` literal → {name: size|None}, else None."""
    if not isinstance(node, ast.Dict):
        return None
    if not all(isinstance(k, ast.Constant) and isinstance(k.value, str)
               for k in node.keys):
        return None
    axes = {}
    for k, v in zip(node.keys, node.values):
        axes[k.value] = (v.value if isinstance(v, ast.Constant)
                         and isinstance(v.value, int) else None)
    return axes


def _name_tuple(node):
    """``("data", "model")`` literal → axis names, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    names = [e.value for e in node.elts
             if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return names or None


def _collect_mesh_axes(tree):
    """Union of axis names over every statically-known mesh in the module.

    Returns None when NO mesh is statically known — SH901 then stays
    silent for the whole module (nothing to check literals against).
    """
    axes = None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        short = _dotted(node.func).rsplit(".", 1)[-1]
        if short not in _MESH_BUILDERS or not node.args:
            continue
        found = _dict_axes(node.args[0])
        if found is None and short == "Mesh" and len(node.args) >= 2:
            names = _name_tuple(node.args[1])
            found = {n: None for n in names} if names else None
        if found is not None:
            axes = dict(axes or {})
            axes.update(found)
    return axes


def _spec_axis_nodes(call):
    """(axis_name, ast_node) for every literal axis entry of a
    ``P(...)`` call, flattening tuple entries (``P(("dp", "tp"))``)."""
    out = []
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            out.append((a.value, a))
        elif isinstance(a, (ast.Tuple, ast.List)):
            for e in a.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append((e.value, e))
    return out


class _ShardingChecker(ast.NodeVisitor):
    def __init__(self, path, findings, mesh_axes, traced_ids=()):
        self.path = path
        self.findings = findings
        self.mesh_axes = mesh_axes  # None: no statically-known mesh
        self.loop_depth = 0
        self.traced_ids = frozenset(traced_ids)
        self.traced_depth = 0

    def _funcdef(self, node):
        traced = id(node) in self.traced_ids
        self.traced_depth += traced
        self.generic_visit(node)
        self.traced_depth -= traced

    visit_FunctionDef = visit_AsyncFunctionDef = _funcdef

    def _flag(self, node, rule, msg):
        self.findings.append(Finding(
            self.path, node.lineno, getattr(node, "col_offset", 0),
            rule, msg))

    # -- loops (SH902 scope) ----------------------------------------------
    def _loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _loop

    # comprehensions iterate too
    def _comp(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_ListComp = visit_SetComp = visit_DictComp = _comp
    visit_GeneratorExp = _comp

    def visit_Call(self, node):
        fn = node.func
        short = _dotted(fn).rsplit(".", 1)[-1]
        # SH901: spec literal vs statically-known mesh axes
        if short in _SPEC_NAMES and self.mesh_axes is not None:
            for name, n in _spec_axis_nodes(node):
                if name not in self.mesh_axes:
                    self._flag(
                        n, "SH901",
                        "PartitionSpec axis %r is not an axis of any mesh "
                        "built in this module (axes: %s) — GSPMD raises "
                        "asynchronously, far from this literal"
                        % (name, sorted(self.mesh_axes)))
        # SH902: resharding inside a loop body
        if self.loop_depth > 0 and isinstance(fn, ast.Attribute):
            if fn.attr == "reshard":
                self._flag(
                    node, "SH902",
                    ".reshard() inside a loop: every iteration moves the "
                    "full array across devices (ICI/DCN traffic, like a "
                    "host sync in a loop) — hoist the placement out of "
                    "the loop or use with_sharding_constraint")
            elif fn.attr == "shard" and _dotted(fn.value).rsplit(
                    ".", 1)[-1] in ("nd", "ndarray"):
                self._flag(
                    node, "SH902",
                    "nd.shard() inside a loop: allocates and moves a "
                    "fresh distributed copy per iteration — shard once "
                    "before the loop")
            elif (fn.attr == "with_sharding_constraint"
                  and not self.traced_depth):
                self._flag(
                    node, "SH902",
                    "eager with_sharding_constraint inside a loop: "
                    "outside a trace it is a registry op that produces "
                    "a re-placed array EVERY iteration — hoist the "
                    "placement out of the loop, or move the loop under "
                    "jit/hybrid_forward where the constraint is a free "
                    "annotation")
        elif (self.loop_depth > 0 and isinstance(fn, ast.Name)
              and fn.id == "with_sharding_constraint"
              and not self.traced_depth):
            self._flag(
                node, "SH902",
                "eager with_sharding_constraint inside a loop: outside "
                "a trace it is a registry op that produces a re-placed "
                "array EVERY iteration — hoist the placement out of the "
                "loop, or move the loop under jit/hybrid_forward where "
                "the constraint is a free annotation")
        self.generic_visit(node)


def run(path, tree, findings=None, strict=False):
    """Run the SH pass over one parsed module; returns the findings list."""
    if findings is None:
        findings = []
    mesh_axes = _collect_mesh_axes(tree)
    from .tracing_safety import collect_traced_functions

    traced_ids = [id(fd) for fd, _f, _names in
                  collect_traced_functions(tree)]
    _ShardingChecker(path, findings, mesh_axes, traced_ids).visit(tree)
    return findings
