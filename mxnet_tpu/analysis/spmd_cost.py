"""Static SPMD cost analysis: predict memory and collective traffic
from shapes + a Mesh + a PartitionSpec rule-set, without running a step.

The reference framework answered "will this fit / how much will it
talk?" empirically — run it and watch it OOM.  GSPMD makes the question
statically decidable: a ``NamedSharding`` determines exactly which
slice of every array each device holds (``verify_spec``'s divisibility
maths), and the Megatron communication pattern is a function of *where
specs disagree at op boundaries* (Shoeybi et al. 2019 §3; GSPMD, Xu et
al. 2021 §3.4).  This module is that function, shared by three
consumers:

- the **planner** (``mxnet_tpu/planner/``) scores candidate rule-sets
  with it (``JitTrainStep(rules="auto")``, ``tools/mxplan.py``);
- **mxlint pass 10** (``planner_check``, SP10xx) runs the same byte
  maths over AST-visible placements — one cost model, two surfaces;
- tests pin the model against **memdump**'s measured per-device
  ``param`` bytes (the acceptance contract: within 10% on the dp=8 and
  megatron-TP dryruns — in practice the parameter term is exact).

Cost model (documented, deliberately simple):

- *per-device bytes* of an array = ``prod(shape) * itemsize`` divided
  by the product of the mesh-axis sizes its spec names — the same
  flattening ``sharding/verify.py`` checks (tuple entries multiply; a
  dim that does not divide its axes is replicated, mirroring
  ``pattern_rule``'s degradation).
- *gradient all-reduce*: a ring all-reduce of N bytes over k devices
  moves ``2*(k-1)/k * N`` bytes per device per step; params sharded on
  the data axis (fsdp-style) reduce-scatter + all-gather instead.
- *tensor-parallel activation collectives*: each row-parallel weight
  implies a forward all-reduce of its output activations, each
  column-parallel / vocab-sharded weight a backward all-reduce of its
  input activations (the f/g pair), sized from a tokens-per-step hint.
- *compile signatures*: a fused train step is ONE executable; a symbol
  graph contributes one signature per distinct (op, attrs, input
  avals) triple — what the persistent compile cache keys on.

Calibration: the constants the model cannot know statically (how many
resident bytes one moved byte is worth, seconds per compile signature)
can be fed from telemetry we already collect — see :class:`Calibration`.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = [
    "itemsize", "partition_factor", "per_device_bytes", "mesh_axes",
    "ParamCost", "CostReport", "Calibration", "analyze_params",
    "analyze_symbol",
]

# dtype name -> bytes per element (covers everything the zoo emits;
# unknown dtypes fall back to 4 so the model degrades, never crashes)
_ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
}


def itemsize(dtype):
    """Bytes per element for a dtype name/np.dtype (default 4)."""
    return _ITEMSIZE.get(str(dtype), 4)


def _entries(spec):
    """Canonical tuple of spec entries: ``None`` and ``P()`` → ``()``;
    list entries become tuples (hashable, JSON-stable)."""
    if spec is None:
        return ()
    out = []
    for e in tuple(spec):
        out.append(tuple(e) if isinstance(e, (tuple, list)) else e)
    return tuple(out)


def _entry_axes(entry):
    return tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)


def mesh_axes(mesh):
    """Normalize any mesh spelling to an ordered ``{axis: size}`` dict.

    Accepts a ``sharding.Mesh``, a raw jax mesh, or a plain axes dict —
    the dict form needs NO devices, so a laptop can plan for a pod
    (``tools/mxplan.py --mesh data=64,model=8``).
    """
    if isinstance(mesh, dict):
        axes = {}
        for name, size in mesh.items():
            if not isinstance(size, int) or size <= 0:
                raise MXNetError(
                    "mesh axis %r needs a positive static size for cost "
                    "analysis; got %r (resolve -1 axes first)"
                    % (name, size))
            axes[str(name)] = size
        return axes
    from .. import sharding as _sharding

    jm = _sharding.as_jax_mesh(mesh)
    if jm is None:
        raise MXNetError("spmd_cost needs a mesh (Mesh, raw jax mesh, "
                         "or {axis: size} dict); got None")
    return dict(jm.shape)


def partition_factor(shape, spec, axes):
    """How many ways a spec splits an array: the product of the sizes of
    every mesh axis it names on a dividing dim.  Mirrors
    ``pattern_rule``'s degradation — a named dim that does not divide
    (or exceeds the rank) contributes 1 (stays replicated)."""
    factor = 1
    for d, entry in enumerate(_entries(spec)):
        if entry is None:
            continue
        size = 1
        for name in _entry_axes(entry):
            if name not in axes:
                raise MXNetError(
                    "spec names axis %r which the mesh does not define "
                    "(axes: %s)" % (name, sorted(axes)))
            size *= axes[name]
        if size > 1 and d < len(shape) and shape[d] % size == 0:
            factor *= size
    return factor


def per_device_bytes(shape, dtype, spec, axes):
    """Bytes ONE device holds for ``shape``/``dtype`` under ``spec``."""
    n = itemsize(dtype)
    for d in shape:
        n *= int(d)
    return n // partition_factor(shape, spec, axes)


def _ring_allreduce(nbytes, k):
    """Per-device bytes moved by a ring all-reduce of an nbytes payload
    over k participants (reduce-scatter + all-gather phases)."""
    return 0 if k <= 1 else (2 * (k - 1) * nbytes) // k


def _ring_gather(nbytes, k):
    """Per-device bytes for one all-gather (or reduce-scatter) phase."""
    return 0 if k <= 1 else ((k - 1) * nbytes) // k


class ParamCost:
    """Predicted placement cost of one parameter."""

    __slots__ = ("name", "shape", "dtype", "spec", "global_bytes",
                 "per_device_bytes", "factor")

    def __init__(self, name, shape, dtype, spec, axes):
        self.name = name
        self.shape = tuple(int(d) for d in shape)
        self.dtype = str(dtype)
        self.spec = _entries(spec)
        n = itemsize(dtype)
        for d in self.shape:
            n *= d
        self.global_bytes = n
        self.factor = partition_factor(self.shape, self.spec, axes)
        self.per_device_bytes = n // self.factor

    @property
    def replicated(self):
        return self.factor == 1

    def spec_str(self):
        if not any(e is not None for e in self.spec):
            return "P()"
        return "P(%s)" % ", ".join(
            repr(e) if e is not None else "None" for e in self.spec)

    def as_dict(self):
        return {"name": self.name, "shape": list(self.shape),
                "dtype": self.dtype,
                "spec": [list(e) if isinstance(e, tuple) else e
                         for e in self.spec],
                "global_bytes": self.global_bytes,
                "per_device_bytes": self.per_device_bytes}


class CostReport:
    """The static prediction for one (param tree, mesh, rule-set)."""

    __slots__ = ("axes", "data_axis", "params",
                 "param_bytes_per_device", "grad_bytes_per_device",
                 "opt_bytes_per_device", "activation_bytes_per_device",
                 "allreduce_bytes", "allgather_bytes",
                 "reducescatter_bytes", "compile_signatures",
                 "step_tokens")

    def __init__(self, axes, data_axis):
        self.axes = dict(axes)
        self.data_axis = data_axis
        self.params = []
        self.param_bytes_per_device = 0
        self.grad_bytes_per_device = 0
        self.opt_bytes_per_device = 0
        self.activation_bytes_per_device = 0
        self.allreduce_bytes = 0
        self.allgather_bytes = 0
        self.reducescatter_bytes = 0
        self.compile_signatures = 1
        self.step_tokens = None

    @property
    def collective_bytes(self):
        """Total per-device collective traffic per step."""
        return (self.allreduce_bytes + self.allgather_bytes
                + self.reducescatter_bytes)

    @property
    def total_bytes_per_device(self):
        """Resident per-device bytes the capacity constraint checks."""
        return (self.param_bytes_per_device + self.grad_bytes_per_device
                + self.opt_bytes_per_device
                + self.activation_bytes_per_device)

    def comm_seconds(self, calibration):
        """Predicted collective seconds per step under a calibration."""
        bps = calibration.comm_bytes_per_second
        return self.collective_bytes / bps if bps else 0.0

    def as_dict(self):
        return {
            "mesh_axes": dict(self.axes), "data_axis": self.data_axis,
            "param_bytes_per_device": self.param_bytes_per_device,
            "grad_bytes_per_device": self.grad_bytes_per_device,
            "opt_bytes_per_device": self.opt_bytes_per_device,
            "activation_bytes_per_device":
                self.activation_bytes_per_device,
            "allreduce_bytes": self.allreduce_bytes,
            "allgather_bytes": self.allgather_bytes,
            "reducescatter_bytes": self.reducescatter_bytes,
            "total_bytes_per_device": self.total_bytes_per_device,
            "collective_bytes": self.collective_bytes,
            "compile_signatures": self.compile_signatures,
            "params": [p.as_dict() for p in self.params],
        }


class Calibration:
    """Constants the static model cannot know, fed from telemetry.

    ``comm_weight`` converts one moved byte into resident-byte units for
    scoring (1.0: a byte of per-step traffic costs as much as a byte of
    residency — the uncalibrated default); ``comm_bytes_per_second``
    turns traffic into seconds; ``compile_seconds_per_signature`` prices
    each extra executable.  :meth:`from_telemetry` pulls what the
    process has already measured: mean ``mxnet_compile_seconds`` per
    compile, the ``mxnet_reshard_bytes_total`` counter, and memdump's
    live per-origin bytes.
    """

    __slots__ = ("comm_weight", "comm_bytes_per_second",
                 "compile_seconds_per_signature", "measured_param_bytes",
                 "measured_reshard_bytes")

    def __init__(self, comm_weight=1.0, comm_bytes_per_second=None,
                 compile_seconds_per_signature=None,
                 measured_param_bytes=None, measured_reshard_bytes=None):
        self.comm_weight = float(comm_weight)
        self.comm_bytes_per_second = comm_bytes_per_second
        self.compile_seconds_per_signature = compile_seconds_per_signature
        self.measured_param_bytes = measured_param_bytes
        self.measured_reshard_bytes = measured_reshard_bytes

    @classmethod
    def from_telemetry(cls, comm_weight=1.0, comm_bytes_per_second=None):
        from ..telemetry import memdump as _memdump
        from ..telemetry import metrics as _metrics

        snap = _metrics.snapshot()
        per_sig = None
        fam = snap.get("mxnet_compile_seconds")
        if fam:
            s = sum(e.get("sum", 0.0) for e in fam["series"])
            c = sum(e.get("count", 0) for e in fam["series"])
            per_sig = (s / c) if c else None
        reshard = None
        fam = snap.get("mxnet_reshard_bytes_total")
        if fam:
            reshard = int(sum(e.get("value", 0) for e in fam["series"]))
        return cls(
            comm_weight=comm_weight,
            comm_bytes_per_second=comm_bytes_per_second,
            compile_seconds_per_signature=per_sig,
            measured_param_bytes=_memdump.device_bytes().get("param"),
            measured_reshard_bytes=reshard)


def _norm_params(params):
    """Normalize a param tree to ``[(name, shape, dtype), ...]``.

    Accepts dicts (``name -> (shape[, dtype])``), ``(name, shape)`` /
    ``(name, shape, dtype)`` tuples, or gluon Parameters."""
    out = []
    if isinstance(params, dict):
        items = params.items()
        for name, v in items:
            if (isinstance(v, (tuple, list)) and len(v) == 2
                    and isinstance(v[0], (tuple, list))):
                out.append((str(name), tuple(v[0]), str(v[1])))
            else:
                out.append((str(name), tuple(v), "float32"))
        return out
    for p in params:
        if hasattr(p, "name") and hasattr(p, "shape"):   # gluon Parameter
            out.append((p.name, tuple(p.shape),
                        str(getattr(p, "dtype", "float32") or "float32")))
        elif len(p) == 2:
            out.append((str(p[0]), tuple(p[1]), "float32"))
        else:
            out.append((str(p[0]), tuple(p[1]), str(p[2])))
    return out


def analyze_params(params, mesh, rule=None, specs=None, data_axis="data",
                   optimizer_slots=0, step_tokens=None, trainable=None):
    """Predict per-device memory + per-step collectives for a param tree.

    Parameters
    ----------
    params : param tree — see :func:`_norm_params` for accepted forms
    mesh : Mesh / raw jax mesh / ``{axis: size}`` dict
    rule : ``fn(name, shape) -> PartitionSpec|None`` (pattern_rule /
        megatron_rule / planner output); mutually exclusive with specs
    specs : explicit ``{name: spec}`` map (planner candidates use this)
    optimizer_slots : per-weight optimizer state arrays (0 sgd,
        1 momentum, 2 adam) — each slot is placed like its weight
    step_tokens : tokens per step (global batch × seq len) sizing the
        tensor-parallel activation collectives; None skips them
    trainable : optional ``set`` of names with gradients (default: all)
    """
    axes = mesh_axes(mesh)
    report = CostReport(axes, data_axis)
    report.step_tokens = step_tokens
    n_data = axes.get(data_axis, 1)
    for name, shape, dtype in _norm_params(params):
        if specs is not None:
            spec = specs.get(name)
        elif rule is not None:
            spec = rule(name, tuple(shape))
        else:
            spec = None
        pc = ParamCost(name, shape, dtype, spec, axes)
        report.params.append(pc)
        report.param_bytes_per_device += pc.per_device_bytes
        is_trainable = trainable is None or name in trainable
        if not is_trainable:
            continue
        report.grad_bytes_per_device += pc.per_device_bytes
        report.opt_bytes_per_device += optimizer_slots * pc.per_device_bytes
        # which axes shard this param?
        named = set()
        for e in pc.spec:
            if e is not None:
                named.update(_entry_axes(e))
        if data_axis in named:
            # fsdp-style: grads reduce-scatter, params all-gather
            report.reducescatter_bytes += _ring_gather(
                pc.per_device_bytes * pc.factor // max(n_data, 1), n_data)
            report.allgather_bytes += _ring_gather(
                pc.per_device_bytes * pc.factor // max(n_data, 1), n_data)
        else:
            # dp grad sync: ring all-reduce of THIS device's grad shard
            report.allreduce_bytes += _ring_allreduce(
                pc.per_device_bytes, n_data)
        # tp activation collectives (the Megatron f/g pair), sized from
        # the tokens hint; activations are batch-sharded over data
        tp = 1
        for a in named - {data_axis}:
            tp *= axes[a]
        if tp > 1 and step_tokens and len(pc.shape) >= 2:
            row_sharded = any(
                e is not None and d >= 1
                for d, e in enumerate(pc.spec))
            dim = pc.shape[0] if row_sharded else pc.shape[-1]
            act = (step_tokens * dim * itemsize(dtype)) // max(n_data, 1)
            report.allreduce_bytes += 2 * _ring_allreduce(act, tp)
    return report


def analyze_symbol(sym, arg_shapes=None, arg_dtypes=None, mesh=None,
                   data_axis="data"):
    """Abstract-interpret a Symbol graph: total activation bytes (per
    device when a mesh is given — activations batch-shard on the data
    axis) and the compile-signature count.

    Reuses graph_verify's propagation: per-node ``jax.eval_shape`` over
    ``ops.registry`` forwards, walking ``Symbol._topo_nodes()``.
    Returns ``(activation_bytes, signatures)``; nodes whose shapes
    cannot be resolved contribute nothing (run ``Symbol.lint()`` first
    for the blame story).
    """
    import jax

    from ..ops import registry as _reg
    from ..symbol.symbol import _op_attrs

    n_data = 1
    if mesh is not None:
        n_data = mesh_axes(mesh).get(data_axis, 1)
    arg_shapes = dict(arg_shapes or {})
    arg_dtypes = dict(arg_dtypes or {})
    act_bytes = 0
    signatures = set()
    vals = {}
    import numpy as _np

    for node in sym._topo_nodes():
        if node.is_variable:
            shp = arg_shapes.get(node.name)
            if shp is None and "__shape__" in node.attrs:
                s = tuple(node.attrs["__shape__"])
                if all(d != 0 for d in s):
                    shp = s
            if shp is None:
                vals[id(node)] = (None,)
            else:
                dt = arg_dtypes.get(node.name, "float32")
                vals[id(node)] = (jax.ShapeDtypeStruct(
                    tuple(shp), _np.dtype(dt)),)
            continue
        n_out = max(1, node.num_outputs)
        try:
            reg = _reg.get(node.op)
        except Exception:
            vals[id(node)] = (None,) * n_out
            continue
        ins = [vals[id(inp)][idx] for inp, idx in node.inputs]
        if any(s is None for s in ins):
            vals[id(node)] = (None,) * n_out
            continue
        attrs = _op_attrs(node, "predict" if reg.needs_mode else None)

        def one(*arrs, _reg_=reg, _attrs_=attrs):
            a = list(arrs)
            if _reg_.needs_rng:
                a = [jax.random.PRNGKey(0)] + a
            out = _reg_.forward(*a, **_attrs_)
            return out if isinstance(out, tuple) else (out,)

        try:
            outs = jax.eval_shape(one, *ins)
        except Exception:
            vals[id(node)] = (None,) * n_out
            continue
        vals[id(node)] = tuple(outs) + (None,) * max(0, n_out - len(outs))
        sig = (node.op, tuple(sorted(attrs.items())),
               tuple((tuple(s.shape), str(s.dtype)) for s in ins))
        try:
            signatures.add(sig)
        except TypeError:
            signatures.add((node.op, id(node)))
        for s in outs:
            b = itemsize(s.dtype)
            for d in s.shape:
                b *= int(d)
            act_bytes += b // n_data
    return act_bytes, len(signatures)
