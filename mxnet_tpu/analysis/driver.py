"""mxlint driver: walk paths, run the static passes, apply suppressions.

Programmatic API (what ``tools/mxlint.py`` and the test suite call):

* ``lint_paths(paths, ...)`` — files/dirs → sorted, suppression-filtered
  findings.
* ``lint_source(source, path, ...)`` — one source string (used by
  ``HybridBlock.lint()``).
* ``lint_block(block)`` — a live ``HybridBlock``: lints its
  ``hybrid_forward`` source (and its children's, recursively).
* ``check_registry(...)`` — RC3xx pass, suppression-filtered.
"""
from __future__ import annotations

import ast
import os
import textwrap

from . import (cache_keys, collective_check, concurrency_check, host_sync,
               lifecycle_check, planner_check, sharding_check,
               tracing_safety, wait_loops)
from .suppressions import SuppressionFile, inline_suppressed

_SKIP_DIRS = frozenset({"__pycache__", ".git", "node_modules", "build",
                        "dist", ".ipynb_checkpoints"})

# rule-band prefix -> pass family, for --pass/--only selection.  RC/EA/GS
# bands don't run through lint_source but are still valid selectors (the
# CLI gates the registry check / symbol files on them).
PASS_BANDS = ("TS", "HS", "RC", "EA", "GS", "CC", "RB", "CS", "SH", "SP",
              "CD", "RL")


def normalize_only(only):
    """Normalize a ``--pass``/``--only`` selection to a tuple of rule-id
    prefixes (``None`` = every pass).  Accepts an iterable or a comma-
    separated string; tokens may be bands (``SP``), families (``SP10``)
    or full rule ids (``SH902``).  Raises ``ValueError`` on a token that
    matches no known rule."""
    if only is None:
        return None
    if isinstance(only, str):
        only = only.split(",")
    from .findings import RULES

    out = []
    for tok in only:
        tok = str(tok).strip().upper()
        if not tok:
            continue
        if not any(r.startswith(tok) for r in RULES):
            raise ValueError(
                "unknown pass/rule selector %r (bands: %s)"
                % (tok, ", ".join(PASS_BANDS)))
        out.append(tok)
    return tuple(out) or None


def rule_selected(rule, only):
    """True when ``rule`` survives a normalized ``only`` selection."""
    return only is None or any(rule.startswith(t) for t in only)


def _band_selected(band, only):
    """True when a pass producing ``band``-rules could emit a selected
    finding (prefix overlap in either direction: ``SP`` selects
    ``SP1001``-producing passes, and so does ``SP1001``)."""
    return only is None or any(t.startswith(band) or band.startswith(t)
                               for t in only)


def _run_static_passes(path, tree, registry_names, findings, strict, only):
    if _band_selected("TS", only):
        tracing_safety.run(path, tree, registry_names, findings)
    if _band_selected("HS", only):
        host_sync.run(path, tree, findings, strict=strict)
    if _band_selected("CC", only):
        collective_check.run(path, tree, findings)
    if _band_selected("RB", only):
        wait_loops.run(path, tree, findings)
    if _band_selected("CS", only):
        cache_keys.run(path, tree, findings, strict=strict)
    if _band_selected("SH", only):
        sharding_check.run(path, tree, findings, strict=strict)
    if _band_selected("SP", only):
        planner_check.run(path, tree, findings, strict=strict)
    if _band_selected("CD", only):
        concurrency_check.run(path, tree, findings)
    if _band_selected("RL", only):
        lifecycle_check.run(path, tree, findings)
    if only is not None:
        findings[:] = [f for f in findings if rule_selected(f.rule, only)]


def default_suppression_file():
    """``tools/mxlint_suppressions.txt`` relative to the repo root."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "mxlint_suppressions.txt")


def registry_op_names():
    """Names valid after ``F.`` in a traced body: registry ops + aliases +
    the public surface of the ndarray/symbol modules (``F`` is one of the
    two at trace time).  ``None`` on import failure → TS105 is skipped."""
    try:
        from ..ops import registry as _reg
        from .. import ndarray as _nd
        from .. import symbol as _sym

        names = set(_reg._REGISTRY) | set(_reg._ALIASES)
        names.update(n for n in dir(_nd) if not n.startswith("__"))
        names.update(n for n in dir(_sym) if not n.startswith("__"))
        return names
    except Exception:
        return None


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS
                                     and not d.startswith("."))
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)
        else:
            raise FileNotFoundError(p)


def _load_suppressions(suppressions):
    if isinstance(suppressions, SuppressionFile):
        return suppressions
    if suppressions is None:
        path = default_suppression_file()
        if os.path.exists(path):
            return SuppressionFile.load(path)
        return SuppressionFile()
    return SuppressionFile.load(suppressions)


def _filter(findings, source_lines, supp):
    kept = []
    for f in findings:
        if source_lines is not None and inline_suppressed(source_lines, f):
            continue
        if supp is not None and supp.suppressed(f):
            continue
        kept.append(f)
    return kept


def lint_source(source, path="<string>", registry_names=None, strict=False,
                suppressions=None, only=None):
    """Lint one source string; returns findings (suppression-filtered).

    ``only``: a pass/rule selection (see :func:`normalize_only`) that
    runs one pass family in isolation."""
    only = normalize_only(only)
    tree = ast.parse(source, filename=path)
    findings = []
    _run_static_passes(path, tree, registry_names, findings, strict, only)
    supp = suppressions if isinstance(suppressions, SuppressionFile) \
        else (SuppressionFile() if suppressions is None
              else _load_suppressions(suppressions))
    return _filter(findings, source.splitlines(), supp)


def lint_paths(paths, registry_names=None, strict=False, suppressions=None,
               relative_to=None, only=None):
    """Lint files/directories.  Returns sorted findings.

    ``registry_names``: pass a set to enable TS105 with it, ``None`` to
    resolve from the live registry (TS105 silently off if that import
    fails).  ``suppressions``: a path, a ``SuppressionFile``, or ``None``
    for the repo default.  ``relative_to``: base dir findings' paths are
    reported (and glob-matched) against; defaults to the repo root when
    linting inside it, else cwd.  ``only``: pass/rule selection
    (:func:`normalize_only`) running one family in isolation.
    """
    only = normalize_only(only)
    if registry_names is None:
        registry_names = registry_op_names()
    supp = _load_suppressions(suppressions)
    if relative_to is None:
        relative_to = os.getcwd()
    all_findings = []
    for fpath in _iter_py_files(paths):
        try:
            with open(fpath, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=fpath)
        except (SyntaxError, UnicodeDecodeError) as e:
            # un-parseable file: real finding, not a crash
            from .findings import Finding
            all_findings.append(Finding(
                _rel(fpath, relative_to), getattr(e, "lineno", 0) or 0, 0,
                "TS101", "file does not parse: %s" % e))
            continue
        rel = _rel(fpath, relative_to)
        findings = []
        _run_static_passes(rel, tree, registry_names, findings, strict,
                           only)
        all_findings.extend(_filter(findings, source.splitlines(), supp))
    all_findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return all_findings


def _rel(path, base):
    ap = os.path.abspath(path)
    ab = os.path.abspath(base)
    if ap.startswith(ab + os.sep):
        return os.path.relpath(ap, ab)
    return path


def verify_symbol_file(path, relative_to=None, suppressions=None):
    """GS5xx-verify a serialized Symbol (``.json`` from ``Symbol.save``).

    A file that doesn't load as a symbol graph yields one GS501 finding
    rather than a crash, mirroring the un-parseable-``.py`` behaviour.
    """
    from .graph_verify import verify_symbol

    supp = _load_suppressions(suppressions)
    if relative_to is None:
        relative_to = os.getcwd()
    rel = _rel(path, relative_to)
    try:
        from ..symbol.symbol import load
        sym = load(path)
    except Exception as e:
        from .findings import Finding
        return _filter([Finding(rel, 0, 0, "GS501",
                                "file does not load as a symbol graph: %s"
                                % e)], None, supp)
    return _filter(verify_symbol(sym, path=rel), None, supp)


def check_registry(suppressions=None, probe=True, strict=False):
    """RC3xx pass over the live registry, suppression-filtered."""
    from . import registry_check

    supp = _load_suppressions(suppressions)
    findings = registry_check.run(probe=probe, strict=strict)
    return _filter(findings, None, supp)


def lint_block(block, registry_names=None, strict=False):
    """Lint a live HybridBlock's ``hybrid_forward`` (and its children's).

    Returns findings whose paths are ``<ClassName>.hybrid_forward``.
    Blocks whose source is unavailable (built in a REPL, C extension) are
    skipped.
    """
    import inspect

    if registry_names is None:
        registry_names = registry_op_names()
    findings = []
    seen = set()
    stack = [block]
    while stack:
        b = stack.pop()
        if id(b) in seen:
            continue
        seen.add(id(b))
        stack.extend(getattr(b, "_children", {}).values())
        fwd = getattr(type(b), "hybrid_forward", None)
        if fwd is None:
            continue
        try:
            source = textwrap.dedent(inspect.getsource(fwd))
        except (OSError, TypeError):
            continue
        pseudo = "%s.hybrid_forward" % type(b).__name__
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        fs = []
        tracing_safety.run(pseudo, tree, registry_names, fs)
        host_sync.run(pseudo, tree, fs, strict=strict)
        findings.extend(_filter(fs, source.splitlines(), SuppressionFile()))
    return findings
