"""HS2xx — host-sync detector: static pass + runtime ``SyncCounter``.

Static side: flags implicit device→host syncs *inside loops* anywhere in a
module (not just traced bodies) — each such sync stalls the PJRT stream
once per iteration, which is exactly the failure mode that shows up in
benchmarks as a mysterious 2-10x slowdown with no error:

* ``HS201`` — ``.asnumpy()``/``.asscalar()``/``.item()`` in a loop body
* ``HS202`` — ``.wait_to_read()``/``waitall()``/``.block_until_ready()``
  in a loop body
* ``HS203`` — ``print()`` of a value assigned from a device op in a loop
  (``repr`` pulls the buffer)
* ``HS204`` — per-batch ``metric.update()`` (advisory; only with
  ``--strict`` — after ``metric.py``'s device-side accumulation this is
  cheap for the built-in metrics, but custom metrics may still pull)

Runtime side: ``SyncCounter`` subscribes to the engine's sync-hook surface
(``Engine.add_hook(fn, kind='sync')``; every ``asnumpy``/``wait_to_read``/
``waitall`` reports through ``Engine.notify_sync``) and aggregates
syncs-per-step, the number to watch when a training loop underperforms.
"""
from __future__ import annotations

import ast
import collections

from .findings import Finding

_PULL_METHODS = frozenset({"asnumpy", "asscalar", "item"})
_WAIT_METHODS = frozenset({"wait_to_read", "block_until_ready"})
_WAIT_FUNCS = frozenset({"waitall"})

# call chains whose results we consider device arrays for HS203 taint:
# nd.zeros(...), mx.nd.ones(...), F.softmax(...), mx.np.arange(...)
_DEVICE_MODULES = frozenset({"nd", "F", "np", "npx"})


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_device_producer(call):
    """Heuristic: does this Call produce a device array?"""
    fname = _dotted(call.func)
    if not fname:
        return False
    head = fname.split(".")[0]
    if head in ("mx", "mxnet", "mxnet_tpu"):
        parts = fname.split(".")
        return len(parts) >= 2 and parts[1] in _DEVICE_MODULES
    return head in _DEVICE_MODULES and "." in fname


class _HostSyncChecker(ast.NodeVisitor):
    def __init__(self, path, findings, strict=False):
        self.path = path
        self.findings = findings
        self.strict = strict
        self.loop_depth = 0
        self.device_names = set()  # names assigned from device producers

    def _flag(self, node, rule, message):
        self.findings.append(Finding(self.path, node.lineno,
                                     getattr(node, "col_offset", 0),
                                     rule, message))

    # -- device-name taint (for HS203 only) -------------------------------
    def visit_Assign(self, node):
        produces = (isinstance(node.value, ast.Call)
                    and _is_device_producer(node.value))
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if produces:
                    self.device_names.add(tgt.id)
                else:
                    self.device_names.discard(tgt.id)
        self.generic_visit(node)

    # -- loops -------------------------------------------------------------
    def _loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = _loop
    visit_While = _loop
    visit_AsyncFor = _loop

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node):
        if self.loop_depth > 0:
            fn = node.func
            if isinstance(fn, ast.Attribute):
                if fn.attr in _PULL_METHODS:
                    self._flag(node, "HS201",
                               ".%s() inside a loop pulls device->host "
                               "every iteration; accumulate on device and "
                               "pull once outside" % fn.attr)
                elif fn.attr in _WAIT_METHODS or fn.attr in _WAIT_FUNCS:
                    self._flag(node, "HS202",
                               ".%s() inside a loop serializes the async "
                               "stream every iteration" % fn.attr)
                elif (self.strict and fn.attr == "update"
                      and "metric" in _dotted(fn.value).lower()):
                    self._flag(node, "HS204",
                               "per-batch metric.update(); built-in "
                               "metrics accumulate on device, custom ones "
                               "may sync per batch")
            elif isinstance(fn, ast.Name):
                if fn.id in _WAIT_FUNCS:
                    self._flag(node, "HS202",
                               "%s() inside a loop blocks on everything "
                               "in flight every iteration" % fn.id)
                elif fn.id == "print":
                    for a in node.args:
                        if (isinstance(a, ast.Name)
                                and a.id in self.device_names):
                            self._flag(node, "HS203",
                                       "printing device array %r in a "
                                       "loop syncs every iteration "
                                       "(format once outside, or pull "
                                       "explicitly)" % a.id)
                            break
        self.generic_visit(node)


def run(path, tree, findings=None, strict=False):
    """Run the HS pass over one parsed module; returns the findings list."""
    if findings is None:
        findings = []
    _HostSyncChecker(path, findings, strict=strict).visit(tree)
    return findings


# ---------------------------------------------------------------------------
# runtime mode
# ---------------------------------------------------------------------------
class SyncCounter:
    """Count device→host syncs per training step via the engine sync hook.

    Usage::

        with SyncCounter() as sc:
            for batch in loader:
                step(batch)
                sc.step()
        print(sc.report())   # {'steps': N, 'total': M, 'per_step': ...}

    A steady-state training step should report ~0 syncs; one sync per step
    means a hidden ``.asnumpy()`` (run ``tools/mxlint.py`` to find it).
    """

    def __init__(self, engine=None):
        if engine is None:
            from ..engine import Engine
            engine = Engine.get()
        self._engine = engine
        self.origins = collections.Counter()
        self.per_step = []
        self._in_step = 0

    # the hook: one call per sync event
    def _on_sync(self, origin):
        self.origins[origin] += 1
        self._in_step += 1

    def install(self):
        self._engine.add_hook(self._on_sync, kind="sync")
        return self

    def uninstall(self):
        self._engine.remove_hook(self._on_sync, kind="sync")

    __enter__ = install

    def __exit__(self, *exc):
        self.uninstall()

    def step(self):
        """Mark a step boundary; returns syncs observed in the step."""
        n, self._in_step = self._in_step, 0
        self.per_step.append(n)
        return n

    @property
    def total(self):
        return sum(self.origins.values())

    def report(self):
        steps = len(self.per_step)
        return {
            "steps": steps,
            "total": self.total,
            "per_step": list(self.per_step),
            "syncs_per_step": (sum(self.per_step) / steps) if steps else 0.0,
            "origins": dict(self.origins),
        }
