"""SP10xx — static planner/cost diagnostics (mxlint pass 10).

The same byte maths the sharding planner scores candidates with
(``spmd_cost``), run over what the AST makes statically visible: mesh
literals (shared with SH9xx's ``_collect_mesh_axes``), declared
capacity constants, and ``nd.shard(<ctor with literal shape>, P(...))``
placements.  Three rules:

* ``SP1001`` — a placement's predicted per-device bytes exceed the
  module's declared capacity (a ``*CAPACITY*`` integer constant, an
  ``os.environ["MXNET_PLANNER_CAPACITY_BYTES"]`` literal, or a
  ``capacity_bytes=`` literal kwarg): a predicted OOM, caught before
  anything runs.  Needs a statically-known mesh in the module.
* ``SP1002`` — a *dominant* placement (≥ a decile — 10% — of the
  module's statically-visible placement bytes, and ≥ 1 MiB) is fully
  replicated onto a multi-device mesh: every device pays the full
  array.  Shard it (``megatron_rule``/``pattern_rule``) or let
  ``rules="auto"`` choose.
* ``SP1003`` — the same array is pinned to two DIFFERENT
  ``with_sharding_constraint`` spec literals inside one loop body:
  GSPMD must insert a reshard between them every iteration of the hot
  loop.  Fires in traced and eager code alike — conflicting specs are
  data movement even where a single constraint would be a free
  annotation.

Like SH901, everything here is conservative: non-literal shapes,
specs, meshes or capacities are never guessed at.
"""
from __future__ import annotations

import ast

from .findings import Finding
from .sharding_check import _collect_mesh_axes
from .spmd_cost import itemsize, partition_factor
from .tracing_safety import _dotted

_CTOR_NAMES = frozenset({"zeros", "ones", "empty", "full"})
_CAPACITY_ENV = "MXNET_PLANNER_CAPACITY_BYTES"
_DOMINANT_SHARE = 10        # dominant = >= total/_DOMINANT_SHARE bytes
_FLOOR_BYTES = 1 << 20      # never flag replication under 1 MiB


def _const_int(node):
    """Fold an integer-literal expression (``64 * 2**20``), else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand)
        return None if v is None else -v
    if isinstance(node, ast.BinOp):
        left, right = _const_int(node.left), _const_int(node.right)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
            if isinstance(node.op, ast.Pow) and 0 <= right <= 64:
                return left ** right
        except (ZeroDivisionError, OverflowError):
            return None
    return None


def _shape_of(node):
    """``(4096, 1024)`` / ``[...]`` literal → shape tuple, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        v = _const_int(node)
        return (v,) if v is not None and v >= 0 else None
    dims = []
    for e in node.elts:
        v = _const_int(e)
        if v is None or v < 0:
            return None
        dims.append(v)
    return tuple(dims)


def _dtype_of(call):
    for kw in call.keywords:
        if kw.arg == "dtype":
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
            name = _dotted(kw.value).rsplit(".", 1)[-1]
            return name or None
    return "float32"


def _ctor_shape(node):
    """``nd/np/jnp.zeros((a, b))``-style call → (shape, dtype), else None."""
    if not isinstance(node, ast.Call) or not node.args:
        return None
    short = _dotted(node.func).rsplit(".", 1)[-1]
    if short not in _CTOR_NAMES:
        return None
    shape = _shape_of(node.args[0])
    if shape is None:
        return None
    return shape, _dtype_of(node)


def _spec_entries(node):
    """``P("data", None)`` / ``PartitionSpec(...)`` literal → entries
    tuple, else None (non-literal entries make the spec unknowable)."""
    if not isinstance(node, ast.Call):
        return None
    if _dotted(node.func).rsplit(".", 1)[-1] not in ("P", "PartitionSpec"):
        return None
    entries = []
    for a in node.args:
        if isinstance(a, ast.Constant) and (a.value is None
                                            or isinstance(a.value, str)):
            entries.append(a.value)
        elif isinstance(a, (ast.Tuple, ast.List)):
            names = []
            for e in a.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, str)):
                    return None
            names = tuple(e.value for e in a.elts)
            entries.append(names)
        else:
            return None
    return tuple(entries)


def _collect_capacity(tree):
    """The module's declared per-device budget: the MINIMUM over every
    statically-evaluable declaration (conservative)."""
    caps = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            v = _const_int(node.value)
            if v is not None and v > 0:
                for t in node.targets:
                    if isinstance(t, ast.Name) and "CAPACITY" in t.id.upper():
                        caps.append(v)
                    elif (isinstance(t, ast.Subscript)
                          and _env_key(t) == _CAPACITY_ENV):
                        caps.append(v)
            # os.environ["MXNET_PLANNER_CAPACITY_BYTES"] = "1024"
            if isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and node.value.value.isdigit():
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and _env_key(t) == _CAPACITY_ENV:
                        caps.append(int(node.value.value))
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "capacity_bytes":
                    v = _const_int(kw.value)
                    if v is not None and v > 0:
                        caps.append(v)
    return min(caps) if caps else None


def _env_key(sub):
    """``os.environ["K"]`` subscript → "K", else None."""
    if _dotted(sub.value).rsplit(".", 1)[-1] != "environ":
        return None
    sl = sub.slice
    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
        return sl.value
    return None


def _placements(tree):
    """Statically-visible placements: ``nd.shard(<literal ctor>,
    P(<literal>))`` calls → [(call_node, shape, dtype, entries)]."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _dotted(node.func).rsplit(".", 1)[-1] != "shard":
            continue
        if not node.args:
            continue
        ctor = _ctor_shape(node.args[0])
        if ctor is None:
            continue
        spec = None
        if len(node.args) >= 2:
            spec = _spec_entries(node.args[1])
        if spec is None:
            for kw in node.keywords:
                if kw.arg == "spec":
                    spec = _spec_entries(kw.value)
        if spec is None:
            continue
        shape, dtype = ctor
        out.append((node, shape, dtype, spec))
    return out


def _nbytes(shape, dtype):
    n = itemsize(dtype)
    for d in shape:
        n *= d
    return n


def _human(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return ("%d%s" % (n, unit) if unit == "B"
                    else "%.1f%s" % (n, unit))
        n /= 1024.0
    return "%d" % n


class _HotLoopSpecs(ast.NodeVisitor):
    """SP1003: per innermost loop body, track the last spec literal each
    receiver was constrained to; a second, different literal means a
    GSPMD reshard every iteration."""

    def __init__(self, path, findings):
        self.path = path
        self.findings = findings
        self._frames = []

    def _loop(self, node):
        self._frames.append({})
        self.generic_visit(node)
        self._frames.pop()

    visit_For = visit_AsyncFor = visit_While = _loop

    def visit_Call(self, node):
        fn = node.func
        is_wsc = ((isinstance(fn, ast.Attribute)
                   and fn.attr == "with_sharding_constraint")
                  or (isinstance(fn, ast.Name)
                      and fn.id == "with_sharding_constraint"))
        if is_wsc and self._frames:
            recv = _dotted(fn.value) if isinstance(fn, ast.Attribute) \
                else (_dotted(node.args[0]) if node.args else "")
            spec_node = None
            if isinstance(fn, ast.Attribute) and node.args:
                spec_node = node.args[0]
            elif isinstance(fn, ast.Name) and len(node.args) >= 2:
                spec_node = node.args[1]
            spec = _spec_entries(spec_node) if spec_node is not None \
                else None
            if recv and spec is not None:
                frame = self._frames[-1]
                prev = frame.get(recv)
                if prev is not None and prev[0] != spec:
                    self.findings.append(Finding(
                        self.path, node.lineno, node.col_offset, "SP1003",
                        "%r is constrained to %s here but to %s at line "
                        "%d inside the same loop body — GSPMD inserts a "
                        "reshard between the two layouts on EVERY "
                        "iteration of this hot loop; pick one layout "
                        "for the loop (or reshard once outside it)"
                        % (recv, _fmt_spec(spec), _fmt_spec(prev[0]),
                           prev[1])))
                frame[recv] = (spec, node.lineno)
        self.generic_visit(node)


def _fmt_spec(entries):
    return "P(%s)" % ", ".join(repr(e) for e in entries)


def run(path, tree, findings=None, strict=False):
    """Run the SP pass over one parsed module; returns the findings."""
    if findings is None:
        findings = []
    axes = _collect_mesh_axes(tree)
    known = {a: s for a, s in (axes or {}).items()
             if isinstance(s, int) and s > 1}
    placements = _placements(tree) if axes is not None else []
    capacity = _collect_capacity(tree)

    def per_device(shape, dtype, entries):
        try:
            return _nbytes(shape, dtype) // partition_factor(
                shape, entries, known)
        except Exception:
            return None     # unknown axis etc. — SH901's business

    # -- SP1001: predicted per-device OOM ---------------------------------
    if capacity is not None:
        for node, shape, dtype, entries in placements:
            pdb = per_device(shape, dtype, entries)
            if pdb is not None and pdb > capacity:
                findings.append(Finding(
                    path, node.lineno, node.col_offset, "SP1001",
                    "placement of shape %s %s under %s needs %s per "
                    "device — over the declared capacity of %s: a "
                    "predicted OOM before anything runs; shard more "
                    "dims, shrink the array, or raise the budget"
                    % (list(shape), dtype, _fmt_spec(entries),
                       _human(pdb), _human(capacity))))

    # -- SP1002: dominant parameter fully replicated ----------------------
    n_devices = 1
    for s in known.values():
        n_devices *= s
    if n_devices > 1 and placements:
        total = sum(_nbytes(shape, dtype)
                    for _n, shape, dtype, _e in placements)
        threshold = max(_FLOOR_BYTES,
                        total // _DOMINANT_SHARE)
        for node, shape, dtype, entries in placements:
            g = _nbytes(shape, dtype)
            try:
                replicated = partition_factor(shape, entries, known) == 1
            except Exception:
                continue    # unknown axis — SH901's business
            if replicated and g >= threshold:
                findings.append(Finding(
                    path, node.lineno, node.col_offset, "SP1002",
                    "a dominant parameter (%s, %s of the %s of "
                    "statically-visible placement bytes here) is fully "
                    "replicated onto a %d-device mesh — every device "
                    "pays the whole array; shard a dim "
                    "(megatron_rule/pattern_rule) or use rules='auto'"
                    % (_human(g),
                       "%d%%" % (100 * g // total) if total else "100%",
                       _human(total), n_devices)))

    # -- SP1003: conflicting specs in a hot loop --------------------------
    _HotLoopSpecs(path, findings).visit(tree)
    return findings
