"""Suppression plumbing: inline comments + the repo-level suppression file.

Inline (on the finding's line, or alone on the line directly above):

* ``# mxlint: disable=TS101`` / ``# mxlint: disable=HS201,HS202``
* ``# mxlint: allow-host-sync`` — shorthand for every sync-shaped rule
  (HS201, HS202, HS203, HS204, TS103); this is the comment the framework's
  own intentional syncs carry (e.g. ``metric.py``'s one pull per ``get()``).

Suppression file (default ``tools/mxlint_suppressions.txt``), one entry per
line, ``#`` comments allowed::

    <path-glob>: RULE[,RULE...]     # why
    op:<op-name>: RULE[,RULE...]    # registry-check allowlist

Path globs match against the path as reported (relative to the lint root)
via ``fnmatch``, so ``mxnet_tpu/kvstore/*`` works.  ``op:`` entries feed the
registry consistency checker, whose findings have pseudo-paths
``op:<name>``.
"""
from __future__ import annotations

import fnmatch
import re

# \d{3,4}: rule ids are 2 letters + 3 digits up to the SH9xx band and
# 4 digits from SP10xx/CD11xx on — a 3-digit-only pattern would silently
# truncate `disable=SP1001` to SP100 and never match the finding
_INLINE = re.compile(r"#\s*mxlint:\s*(allow-host-sync|disable="
                     r"([A-Z]{2}\d{3,4}(?:\s*,\s*[A-Z]{2}\d{3,4})*))")

_ALLOW_HOST_SYNC = frozenset({"HS201", "HS202", "HS203", "HS204", "TS103"})


def inline_suppressed(source_lines, finding):
    """True if the finding's own line (or a pure-comment line directly
    above it) carries a matching ``# mxlint:`` comment."""
    for lineno in (finding.line, finding.line - 1):
        if not 1 <= lineno <= len(source_lines):
            continue
        text = source_lines[lineno - 1]
        if lineno != finding.line and not text.lstrip().startswith("#"):
            continue
        for m in _INLINE.finditer(text):
            if m.group(1) == "allow-host-sync":
                if finding.rule in _ALLOW_HOST_SYNC:
                    return True
            else:
                rules = {r.strip() for r in m.group(2).split(",")}
                if finding.rule in rules:
                    return True
    return False


class SuppressionFile:
    """Parsed ``mxlint_suppressions.txt``; answers path/rule queries."""

    def __init__(self, entries=()):
        # list of (path_glob, frozenset(rules))
        self.entries = list(entries)

    @classmethod
    def load(cls, path):
        entries = []
        with open(path, encoding="utf-8") as f:
            for raw in f:
                line = raw.split("#", 1)[0].strip()
                if not line:
                    continue
                if ":" not in line:
                    raise ValueError(
                        "malformed suppression entry %r (want "
                        "'<glob>: RULE[,RULE]')" % raw.strip())
                # rsplit: 'op:<name>: RULES' keeps 'op:<name>' intact
                glob, rules = line.rsplit(":", 1)
                entries.append((glob.strip(),
                                frozenset(r.strip() for r in
                                          rules.split(",") if r.strip())))
        return cls(entries)

    def suppressed(self, finding):
        for glob, rules in self.entries:
            if finding.rule not in rules and "*" not in rules:
                continue
            if fnmatch.fnmatch(finding.path, glob):
                return True
        return False
