"""RC3xx — op-registry consistency checker.

``ops/registry.py`` claims shape/dtype inference "falls out of
``jax.eval_shape`` on the same function, so ops can never disagree with
their inference".  True for shapes — but the registry still carries
*declared* metadata (``num_outputs``, ``input_names``, docs) that nothing
cross-checked until now.  This pass closes the loop abstractly (no device
work, everything under ``jax.eval_shape``):

* ``RC301`` — declared ``num_outputs`` vs the forward's actual output
  count when probed with abstract inputs.
* ``RC302`` — registered op without a docstring.
* ``RC303`` — ``input_names`` empty/duplicated for a non-variadic op, or
  colliding with ``attr_names`` (the positional-attr dispatcher would
  mis-bind).
* ``RC304`` — an alias that shadows a primary op name (``get()`` resolves
  the primary first, so the alias silently never fires).
* ``RC305`` — a float-valued op whose forward has no abstract ``jax.vjp``
  — a gradient is expected (autograd's lazy tape will vjp it on backward)
  but tracing one fails.

Probing is best-effort: ops whose forwards need attrs or specific ranks
reject the generic probe shapes with a shape/type error — those are
*skipped*, not flagged (the check only asserts on ops it could actually
evaluate).  Known-intentional exceptions live in the suppression file as
``op:<name>: RULE`` entries.
"""
from __future__ import annotations

from .findings import Finding

# probe shape-sets tried in order until one traces (all inputs share a
# shape; rank variety covers elementwise, matmul-ish and NHWC-ish ops)
_PROBE_SHAPES = ((2, 3), (2, 3, 4), (1, 4, 8, 8), (4,))

# int-valued / index-producing / mode-gated ops legitimately have no vjp;
# the built-in list covers jax primitives' hard non-differentiables, the
# suppression file covers op-specific judgment calls
_NONDIFF_HINTS = ("argmax", "argmin", "argsort", "topk", "one_hot", "shape",
                  "size", "round", "floor", "ceil", "sign", "equal",
                  "not_equal", "greater", "lesser", "logical", "random",
                  "sample", "multinomial")


def _probe_args(reg, shape, jnp):
    import jax

    args = []
    if reg.needs_rng:
        args.append(jax.ShapeDtypeStruct((2,), jnp.uint32))
    n = max(len(reg.input_names), 1) if not reg.variadic else 2
    for _ in range(n):
        args.append(jax.ShapeDtypeStruct(shape, jnp.float32))
    return tuple(args)


def _eval_op(reg):
    """Try to eval_shape the raw forward; returns (outputs, args) or None."""
    import jax
    import jax.numpy as jnp

    attrs = {}
    if reg.needs_mode:
        attrs["_mode"] = "predict"
    for shape in _PROBE_SHAPES:
        args = _probe_args(reg, shape, jnp)
        try:
            out = jax.eval_shape(lambda *xs: reg.forward(*xs, **attrs), *args)
        except Exception:
            continue
        outs = out if isinstance(out, tuple) else (out,)
        return outs, args
    return None


def run(registry=None, aliases=None, findings=None, probe=True,
        strict=False):
    """Check every registered op; returns the findings list.

    Findings carry pseudo-paths ``op:<name>`` so the suppression file can
    allowlist individual ops.  ``num_outputs=-1`` is the registry's
    "variadic outputs" convention (split/topk/multi-tensor optimizers) and
    exempts an op from output-count checks.  ``strict`` enables the
    advisory RC302 docstring rule.
    """
    if registry is None or aliases is None:
        from ..ops import registry as _reg
        registry = _reg._REGISTRY if registry is None else registry
        aliases = _reg._ALIASES if aliases is None else aliases
    if findings is None:
        findings = []

    for alias_name in sorted(aliases):
        if alias_name in registry:
            findings.append(Finding(
                "op:%s" % alias_name, 0, 0, "RC304",
                "alias %r also names a primary op; get() always resolves "
                "the primary, the alias target %r is unreachable"
                % (alias_name, aliases[alias_name])))

    for name in sorted(registry):
        reg = registry[name]
        path = "op:%s" % name
        if strict and not (reg.doc or "").strip():
            findings.append(Finding(
                path, 0, 0, "RC302",
                "op %r has no docstring (OpReg.doc is empty)" % name))
        if not reg.variadic:
            if len(set(reg.input_names)) != len(reg.input_names):
                findings.append(Finding(
                    path, 0, 0, "RC303",
                    "op %r declares duplicate input_names %r"
                    % (name, reg.input_names)))
            overlap = set(reg.input_names) & set(reg.attr_names)
            if overlap:
                findings.append(Finding(
                    path, 0, 0, "RC303",
                    "op %r: names %r are both inputs and attrs — the "
                    "positional-attr binder would mis-bind"
                    % (name, sorted(overlap))))
        if reg.num_outputs < 1 and reg.num_outputs != -1:
            findings.append(Finding(
                path, 0, 0, "RC303",
                "op %r declares num_outputs=%r" % (name, reg.num_outputs)))

        if not probe:
            continue
        probed = _eval_op(reg)
        if probed is None:
            continue  # needs attrs/specific ranks: skipped, not flagged
        outs, args = probed
        if reg.num_outputs != -1 and len(outs) != reg.num_outputs:
            findings.append(Finding(
                path, 0, 0, "RC301",
                "op %r declares num_outputs=%d but its forward returned "
                "%d output(s) under jax.eval_shape"
                % (name, reg.num_outputs, len(outs))))
            continue
        lname = name.lower()
        if (all(o.dtype.kind == "f" for o in outs)
                and not any(h in lname for h in _NONDIFF_HINTS)
                and not reg.needs_rng):
            import jax

            attrs = {"_mode": "predict"} if reg.needs_mode else {}

            def fwd(*xs):
                out = reg.forward(*xs, **attrs)
                return out if isinstance(out, tuple) else (out,)

            try:
                jax.eval_shape(lambda *xs: jax.vjp(fwd, *xs), *args)
            except Exception as e:
                findings.append(Finding(
                    path, 0, 0, "RC305",
                    "op %r: float-valued forward has no abstract jax.vjp "
                    "(%s: %s) — gradient expected but untraceable"
                    % (name, type(e).__name__, str(e).split("\n")[0][:120])))
    return findings
