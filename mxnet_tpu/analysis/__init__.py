"""Framework-aware static analysis (``mxlint``) + runtime auditors.

The paper's central bet — MXNet's async dependency engine collapsing onto
XLA's enqueue-order execution (see ``engine.py``) — holds only while user
and framework code keeps two contracts:

1. nothing inside a hybridized/traced region forces a host round-trip or a
   Python-level data-dependent branch (XLA traces would either crash or
   silently bake in one branch), and
2. every device→host sync on the eager path is *intentional*, because each
   one stalls the PJRT stream the engine relies on for overlap.

This package enforces both, statically and at runtime, with twelve
passes:

* **tracing-safety lint** (``TS1xx``, ``tracing_safety``) — AST pass over
  ``hybrid_forward`` bodies and jit-wrapped functions: data-dependent
  ``if``/``while`` on array values, host coercions, in-place mutation of
  traced arrays, calls to ops absent from ``ops.registry``.
* **host-sync detector** (``HS2xx``, ``host_sync``) — static flagging of
  implicit device→host syncs inside loops, plus a runtime ``SyncCounter``
  built on the engine's sync-hook surface (``Engine.add_hook(fn,
  kind='sync')``) reporting syncs-per-step.
* **engine dependency auditor** (``EA4xx``, ``engine_audit``) — runtime
  checker (``MXNET_ENGINE_AUDIT=1``) validating read/write var sets at
  ``Engine.push``: out-of-band writes that skip ``Var.on_write``,
  overlapping write sets from concurrent threads, version regressions.
* **registry consistency checker** (``RC3xx``, ``registry_check``) — every
  registered op must have a coherent ``num_outputs``/``input_names``/doc
  and, where a gradient is expected, a differentiable forward under
  ``jax.eval_shape``.
* **graph verifier** (``GS5xx``, ``graph_verify``) — per-node abstract
  interpreter over ``Symbol._topo_nodes()`` that blames shape/dtype
  failures on the offending node (``Symbol.lint()``, the
  ``MXNET_GRAPH_VERIFY=1`` bind pre-flight, ``.json`` files on the CLI).
* **collective consistency checker** (``CC6xx``, ``collective_check``) —
  static checks on literal collective programs (unknown axis names,
  non-permutation ``ppermute`` perms, collectives under data-dependent
  branches) plus runtime pre-dispatch validators used by
  ``parallel/pipeline.py`` and ``parallel/dist_kvstore.py``.
* **robustness checker** (``RB7xx``, ``wait_loops``) — flags
  ``Condition.wait(timeout=...)`` whose return value is ignored inside a
  re-check loop with no deadline: the exact silent-hang shape that
  wedged the distributed tier before the fault-tolerance work
  (``docs/fault_tolerance.md``).
* **compile-cache key hygiene** (``CS8xx``, ``cache_keys``) — op attrs
  that fragment the executable cache: set/dict/fresh-array/lambda attr
  values are unhashable or identity-keyed, so the call retraces every
  time and never hits the persistent disk cache
  (``compile_cache.py``); explicit ``attr=None`` needlessly splits
  entries (advisory).
* **sharding hygiene** (``SH9xx``, ``sharding_check``) — PartitionSpec
  literals naming axes no statically-known mesh defines; reshard /
  ``nd.shard`` / eager ``with_sharding_constraint`` inside loop bodies
  (cross-device data movement per iteration).
* **planner/cost diagnostics** (``SP10xx``, ``planner_check``) — the
  sharding planner's byte maths (``spmd_cost``) run statically:
  placements predicted to exceed a declared per-device capacity,
  dominant parameters fully replicated onto a multi-device mesh,
  conflicting spec constraints inside one hot loop.
* **concurrency discipline** (``CD11xx``, ``concurrency_check``) — per
  class that owns locks: guarded fields accessed unlocked on
  thread-reachable paths, lock-order inversions across call edges,
  blocking calls and user-visible callbacks under a lock, manual
  ``acquire()`` without try/finally.  Runtime half:
  ``MXNET_LOCKCHECK=1`` (``testing/lockcheck.py``) proxies the
  framework's named locks, builds the acquisition-order graph live and
  raises ``LockCycleError`` on deadlock *potential*.
* **ownership & lifecycle discipline** (``RL12xx``, ``lifecycle_check``)
  — path-sensitive acquire/release tracking over the repo's handle
  kinds (arena pages, sockets, temp files/dirs, request futures,
  threads): leaks on early returns/raises, uses in the unprotected
  window between acquire and cleanup registration, futures with
  reachable never-resolved paths, double-free / use-after-release,
  broad swallows inside cleanup scopes.  Runtime half:
  ``MXNET_RESCHECK=1`` (``testing/rescheck.py``) — a tracked-handle
  registry reporting live handles at ``drain()``/``stop()``/atexit as
  ``ResourceLeakError`` with creation stacks.

CLI: ``python tools/mxlint.py mxnet_tpu/ examples/`` (the repo's own source
is a permanent lint target; intentional syncs carry
``# mxlint: allow-host-sync`` or an entry in
``tools/mxlint_suppressions.txt``; ``--pass SP10`` runs one pass family
in isolation).  Docs: ``docs/static_analysis.md``.
"""
from __future__ import annotations

from .findings import Finding, RULES, SEVERITY, rule_doc, severity_at_least
from .driver import (lint_paths, lint_source, lint_block, check_registry,
                     verify_symbol_file, normalize_only, rule_selected)
from .spmd_cost import (Calibration, CostReport, analyze_params,
                        analyze_symbol, per_device_bytes)
from .graph_verify import verify_symbol, input_consumers, blame_unresolved
from .collective_check import check_axis, check_ppermute
from .host_sync import SyncCounter
from .engine_audit import EngineAudit, EngineAuditError, install, uninstall

__all__ = [
    "Finding", "RULES", "SEVERITY", "rule_doc", "severity_at_least",
    "lint_paths", "lint_source", "lint_block", "check_registry",
    "verify_symbol_file", "normalize_only", "rule_selected",
    "Calibration", "CostReport", "analyze_params", "analyze_symbol",
    "per_device_bytes",
    "verify_symbol", "input_consumers", "blame_unresolved",
    "check_axis", "check_ppermute",
    "SyncCounter",
    "EngineAudit", "EngineAuditError", "install", "uninstall",
]
