"""Finding record + the rule table shared by every mxlint pass.

Rule ID bands (stable, documented in ``docs/static_analysis.md``):

* ``TS1xx`` — tracing safety (static, hybrid_forward / jitted bodies only)
* ``HS2xx`` — host-sync hygiene (static, any code)
* ``RC3xx`` — op-registry consistency (semi-static, needs an importable
  registry)
* ``EA4xx`` — engine dependency audit (runtime only; listed here so the
  audit raises with the same vocabulary the linter reports in)
"""
from __future__ import annotations


# rule id -> (slug, default-on, one-line doc)
RULES = {
    "TS101": ("data-dependent-branch", True,
              "`if` on a traced array value — XLA tracing bakes in one "
              "branch (or crashes on ConcretizationError)"),
    "TS102": ("data-dependent-loop", True,
              "`while` on a traced array value — trip count cannot be "
              "staged into the graph"),
    "TS103": ("host-coercion-in-trace", True,
              ".asnumpy()/.asscalar()/.item()/float()/int()/bool() on a "
              "traced array forces a device->host sync mid-trace"),
    "TS104": ("traced-array-mutation", True,
              "in-place subscript store into a traced array — functional "
              "arrays ignore it silently under tracing"),
    "TS105": ("unregistered-op", True,
              "call to an F.<op> absent from ops.registry "
              "(_REGISTRY/_ALIASES) — fails only at first trace"),
    "HS201": ("host-sync-in-loop", True,
              ".asnumpy()/.asscalar()/.item() inside a loop — one "
              "device->host pull per iteration stalls the async stream"),
    "HS202": ("blocking-wait-in-loop", True,
              "wait_to_read()/waitall()/block_until_ready() inside a loop "
              "serializes dispatch against the device"),
    "HS203": ("ndarray-print-in-loop", True,
              "printing a device array inside a loop implicitly syncs "
              "every iteration (repr pulls the buffer)"),
    "HS204": ("per-batch-metric-update", False,
              "metric.update() per batch may pull device buffers each "
              "iteration; accumulate on device and pull once per get() "
              "(advisory, enabled with --strict)"),
    "RC301": ("num-outputs-mismatch", True,
              "registered num_outputs disagrees with the forward's actual "
              "output count under jax.eval_shape"),
    "RC302": ("missing-op-doc", False,
              "registered op has no docstring (advisory — most ops are "
              "registered lambdas; enabled with --strict)"),
    "RC303": ("incoherent-input-names", True,
              "input_names empty/duplicated or colliding with attr names "
              "for a non-variadic op"),
    "RC304": ("alias-shadows-primary", True,
              "an alias name collides with a primary op name (lookup "
              "would silently prefer the primary)"),
    "RC305": ("non-differentiable-forward", True,
              "float-valued op's forward fails jax.vjp under eval_shape — "
              "gradient expected but untraceable"),
    "EA401": ("out-of-band-write", True,
              "a var's version changed outside Engine.push — a write "
              "skipped Var.on_write / the declared write set"),
    "EA402": ("overlapping-concurrent-writes", True,
              "two threads pushed overlapping write sets concurrently"),
    "EA403": ("version-regression", True,
              "a var's version moved backwards — state was rolled back "
              "or a stale Var was resurrected"),
}


def rule_doc(rule_id):
    slug, _default, doc = RULES[rule_id]
    return "%s (%s): %s" % (rule_id, slug, doc)


class Finding:
    """One lint finding, printable as ``path:line:col: RULE message``."""

    __slots__ = ("path", "line", "col", "rule", "message")

    def __init__(self, path, line, col, rule, message):
        self.path = path
        self.line = line
        self.col = col
        self.rule = rule
        self.message = message

    @property
    def slug(self):
        return RULES[self.rule][0]

    def __repr__(self):
        return "Finding(%s:%s:%s %s)" % (self.path, self.line, self.col,
                                         self.rule)

    def __str__(self):
        return "%s:%d:%d: %s [%s] %s" % (
            self.path, self.line, self.col, self.rule, self.slug,
            self.message)

    def as_dict(self):
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "slug": self.slug,
                "message": self.message}
