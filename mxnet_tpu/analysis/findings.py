"""Finding record + the rule table shared by every mxlint pass.

Rule ID bands (stable, documented in ``docs/static_analysis.md``):

* ``TS1xx`` — tracing safety (static, hybrid_forward / jitted bodies only)
* ``HS2xx`` — host-sync hygiene (static, any code)
* ``RC3xx`` — op-registry consistency (semi-static, needs an importable
  registry)
* ``EA4xx`` — engine dependency audit (runtime only; listed here so the
  audit raises with the same vocabulary the linter reports in)
* ``GS5xx`` — graph verification (per-node abstract interpretation over
  ``Symbol._topo_nodes()``; ``Symbol.lint()``, ``MXNET_GRAPH_VERIFY=1``
  bind pre-flight, and ``.json`` symbol files passed to the CLI)
* ``CC6xx`` — collective consistency (static AST pass over ``parallel/``
  programs + runtime pre-dispatch validators in ``pipeline.py`` /
  ``dist_kvstore.py``, which raise with the same vocabulary)
* ``RB7xx`` — robustness (static; unbounded condition-wait loops that
  turn a dead peer into a silent hang)
* ``CS8xx`` — compile-cache key hygiene (static AST over op invocations;
  attr values that make the executable cache key unhashable or
  identity-keyed fragment both the in-process jit cache and the
  persistent disk cache — see ``compile_cache.py``)
* ``SH9xx`` — sharding hygiene (static AST over ``PartitionSpec``
  literals and reshard call sites; the dynamic half of the same
  contract is ``MXNET_SHARDING_VERIFY`` — see ``docs/sharding.md``)
* ``SP10xx`` — planner/cost diagnostics (static byte maths from
  ``analysis/spmd_cost.py`` — the same model the sharding planner
  scores candidates with — over AST-visible meshes, capacities and
  placements; see ``docs/static_analysis.md`` Pass 10)
* ``CD11xx`` — concurrency discipline (static AST over classes that
  own locks: guarded-field races, lock-order inversions, blocking
  calls and user-visible callbacks under a lock, leaked manual
  acquires; the dynamic half is ``MXNET_LOCKCHECK=1`` —
  ``testing/lockcheck.py`` — which enforces the same acquisition-order
  contract on live interleavings)
* ``RL12xx`` — ownership & lifecycle discipline (path-sensitive
  acquire/release tracking over the repo's handle kinds: arena page
  lists, sockets, temp files/dirs, request futures, threads — leaks
  on early exits, unprotected raise windows between acquire and
  cleanup registration, hung-future paths, double-free /
  use-after-release, broad swallows inside cleanup scopes; the
  dynamic half is ``MXNET_RESCHECK=1`` — ``testing/rescheck.py`` —
  a tracked-handle registry reporting live handles at drain/stop/
  atexit with creation stacks)
"""
from __future__ import annotations


# rule id -> (slug, default-on, one-line doc)
RULES = {
    "TS101": ("data-dependent-branch", True,
              "`if` on a traced array value — XLA tracing bakes in one "
              "branch (or crashes on ConcretizationError)"),
    "TS102": ("data-dependent-loop", True,
              "`while` on a traced array value — trip count cannot be "
              "staged into the graph"),
    "TS103": ("host-coercion-in-trace", True,
              ".asnumpy()/.asscalar()/.item()/float()/int()/bool() on a "
              "traced array forces a device->host sync mid-trace"),
    "TS104": ("traced-array-mutation", True,
              "in-place subscript store into a traced array — functional "
              "arrays ignore it silently under tracing"),
    "TS105": ("unregistered-op", True,
              "call to an F.<op> absent from ops.registry "
              "(_REGISTRY/_ALIASES) — fails only at first trace"),
    "HS201": ("host-sync-in-loop", True,
              ".asnumpy()/.asscalar()/.item() inside a loop — one "
              "device->host pull per iteration stalls the async stream"),
    "HS202": ("blocking-wait-in-loop", True,
              "wait_to_read()/waitall()/block_until_ready() inside a loop "
              "serializes dispatch against the device"),
    "HS203": ("ndarray-print-in-loop", True,
              "printing a device array inside a loop implicitly syncs "
              "every iteration (repr pulls the buffer)"),
    "HS204": ("per-batch-metric-update", False,
              "metric.update() per batch may pull device buffers each "
              "iteration; accumulate on device and pull once per get() "
              "(advisory, enabled with --strict)"),
    "RC301": ("num-outputs-mismatch", True,
              "registered num_outputs disagrees with the forward's actual "
              "output count under jax.eval_shape"),
    "RC302": ("missing-op-doc", False,
              "registered op has no docstring (advisory — most ops are "
              "registered lambdas; enabled with --strict)"),
    "RC303": ("incoherent-input-names", True,
              "input_names empty/duplicated or colliding with attr names "
              "for a non-variadic op"),
    "RC304": ("alias-shadows-primary", True,
              "an alias name collides with a primary op name (lookup "
              "would silently prefer the primary)"),
    "RC305": ("non-differentiable-forward", True,
              "float-valued op's forward fails jax.vjp under eval_shape — "
              "gradient expected but untraceable"),
    "EA401": ("out-of-band-write", True,
              "a var's version changed outside Engine.push — a write "
              "skipped Var.on_write / the declared write set"),
    "EA402": ("overlapping-concurrent-writes", True,
              "two threads pushed overlapping write sets concurrently"),
    "EA403": ("version-regression", True,
              "a var's version moved backwards — state was rolled back "
              "or a stale Var was resurrected"),
    "GS501": ("node-shape-mismatch", True,
              "an op node's shape/dtype check failed under per-node "
              "abstract evaluation — the finding names the op, the node, "
              "its input shapes and the producing nodes"),
    "GS502": ("unresolved-input", True,
              "a graph input's shape cannot be inferred or hinted — the "
              "finding names the first consumer node that needed it"),
    "GS503": ("duplicate-node-name", True,
              "two graph nodes share one name — name-keyed bindings and "
              "serialization silently alias one of them"),
    "GS504": ("dead-argument", True,
              "a supplied argument/shape binding matches no graph input — "
              "the executor would silently drop it"),
    "GS505": ("dtype-conflict", True,
              "a multi-input node joins float inputs of different widths "
              "— silent promotion hides a precision/memory bug"),
    "CC601": ("unknown-axis-name", True,
              "a collective/shard_map spec names an axis absent from the "
              "mesh — fails only at dispatch, or deadlocks multihost"),
    "CC602": ("non-permutation-ppermute", True,
              "a ppermute perm with duplicate sources/destinations or "
              "out-of-range ranks — lanes silently receive zeros or the "
              "program is rejected at lowering"),
    "CC603": ("collective-under-branch", True,
              "a collective inside a data-dependent branch — ranks that "
              "disagree on the predicate deadlock the collective"),
    "CC604": ("pipeline-schedule-mismatch", True,
              "pipeline stage/microbatch geometry disagrees with the mesh "
              "axis (stacked leading dim != n_stages, empty schedule)"),
    "CC605": ("kvstore-key-divergence", True,
              "dist-kvstore push/pull key sets diverge from the "
              "initialized schema — sync mode barriers per key and "
              "divergent sets deadlock the round"),
    "RB701": ("wait-without-deadline", True,
              "Condition.wait(timeout=...) return ignored inside a "
              "re-check loop with no deadline — a dead peer re-waits "
              "forever (silent hang); track a monotonic deadline and "
              "raise naming what's missing"),
    "CS801": ("unhashable-op-attr", True,
              "op attr is a set literal or a fresh np/jnp/nd array — "
              "unhashable or identity-keyed in the executable cache key, "
              "so every call recompiles and never hits the persistent "
              "disk cache"),
    "CS802": ("identity-keyed-attr", True,
              "op attr is a lambda — each evaluation mints a new function "
              "object (new cache key) → retrace despite identical "
              "behaviour; hoist to a module-level def"),
    "CS803": ("unfrozen-dict-attr", True,
              "op attr is a dict literal — unhashable in the executable "
              "cache key; freeze to tuple(sorted(d.items()))"),
    "CS804": ("explicit-none-attr", False,
              "attr passed explicitly as None enters the cache key and "
              "compiles a separate executable from call sites that omit "
              "it (advisory, enabled with --strict)"),
    "SH901": ("unknown-mesh-axis", True,
              "a PartitionSpec literal names an axis no statically-"
              "visible mesh defines — surfaces only as an async XLA "
              "error far from the typo"),
    "SH902": ("reshard-in-loop", True,
              "reshard()/nd.shard()/eager with_sharding_constraint "
              "inside a loop — cross-device data movement every "
              "iteration; hoist the placement out of the loop (in "
              "traced code a single with_sharding_constraint is a free "
              "annotation and stays clean)"),
    "SP1001": ("predicted-oom", True,
               "a statically-visible placement needs more per-device "
               "bytes than the module's declared capacity "
               "(*CAPACITY* constant / MXNET_PLANNER_CAPACITY_BYTES / "
               "capacity_bytes=) — a predicted OOM before anything "
               "runs"),
    "SP1002": ("replicated-dominant-param", True,
               "a dominant parameter (>= a decile of the module's "
               "statically-visible placement bytes, >= 1 MiB) is fully "
               "replicated onto a multi-device mesh — shard a dim "
               "(megatron_rule/pattern_rule) or use rules='auto'"),
    "SP1003": ("conflicting-specs-in-loop", True,
               "the same array is pinned to two different "
               "with_sharding_constraint spec literals inside one loop "
               "body — GSPMD inserts a reshard between the layouts "
               "every iteration of the hot loop"),
    "CD1101": ("unguarded-field-access", True,
               "a field predominantly accessed under a lock is read or "
               "written with no lock held on a thread-reachable path — "
               "a racing writer can interleave mid-operation"),
    "CD1102": ("lock-order-inversion", True,
               "two code paths acquire the same pair of locks in "
               "opposite orders — some thread interleaving deadlocks; "
               "reported with both acquisition paths"),
    "CD1103": ("blocking-call-under-lock", True,
               "socket recv/accept, Future.result, host-sync pulls, "
               "time.sleep or an untimed condition-wait while holding a "
               "lock — every thread needing that lock stalls behind the "
               "block, forever if the peer is dead"),
    "CD1104": ("acquire-without-finally", True,
               "manual lock.acquire() not immediately followed by "
               "try/finally release — any exception in between leaks "
               "the lock permanently; use `with`"),
    "CD1105": ("callback-under-lock", True,
               "set_result/set_exception, a done-event .set(), or a "
               "hook/callback invoked while holding a lock — user code "
               "runs inside the critical section and can re-enter it "
               "(deadlock) or stretch the hold time unboundedly"),
    "RL1201": ("acquire-without-release", True,
               "a handle (arena pages, socket, temp file/dir, thread) "
               "is acquired but a reachable early return/raise exits "
               "the function with it neither released nor handed off — "
               "the resource leaks on that path"),
    "RL1202": ("unprotected-acquire-window", True,
               "statements that can raise run between acquiring an OS "
               "resource (socket, temp file/dir) and registering its "
               "cleanup (try/finally or an except that closes and "
               "re-raises) — an exception in the window leaks the "
               "handle; move the try up to the acquire"),
    "RL1203": ("future-neither-resolved-nor-cancelled", True,
               "a Request/Future is created but some reachable path "
               "exits without set_result/set_exception/cancel and "
               "without handing it off — a waiter on that path hangs "
               "forever"),
    "RL1204": ("double-free-or-use-after-release", True,
               "the same handle is released twice, or used after its "
               "release, along one path — the second owner (page "
               "reuse, fd recycling) sees the corruption, far from "
               "this line"),
    "RL1205": ("swallow-in-cleanup", True,
               "a bare/broad `except: pass` inside a cleanup scope (a "
               "finally block, a release-calling try, or a close/stop/"
               "drain-shaped method) — a failed release looks exactly "
               "like a successful one; catch the narrow OSError or "
               "record the failure"),
}

# rule id -> severity; rules not listed are "error".  Ordering:
# note < warn < error (``--fail-on`` thresholds exit status on this).
SEVERITY = {
    "HS201": "warn",
    "HS202": "warn",
    "HS203": "warn",
    "HS204": "note",
    "RC302": "note",
    "GS504": "warn",
    "GS505": "warn",
    "CS802": "warn",
    "CS803": "warn",
    "CS804": "note",
    "SH902": "warn",
    "SP1002": "warn",
    "SP1003": "warn",
    # CD1101/CD1103/CD1105 are heuristic (guarded-majority inference,
    # blocking/callback vocabularies) -> warn; CD1102 (a provable
    # inversion) and CD1104 (a provable leak path) stay errors.
    "CD1101": "warn",
    "CD1103": "warn",
    "CD1105": "warn",
    # RL1203 (hung-future risk) and RL1205 (swallow heuristics) infer
    # intent from vocabularies -> warn; RL1201/RL1202/RL1204 are
    # provable leak/corruption paths and stay errors.
    "RL1203": "warn",
    "RL1205": "warn",
}

_SEVERITY_RANK = {"note": 0, "warn": 1, "error": 2}


def severity_at_least(finding, threshold):
    """True if ``finding``'s severity is at or above ``threshold``."""
    return _SEVERITY_RANK[finding.severity] >= _SEVERITY_RANK[threshold]


def rule_doc(rule_id):
    slug, _default, doc = RULES[rule_id]
    return "%s (%s): %s" % (rule_id, slug, doc)


class Finding:
    """One lint finding, printable as ``path:line:col: RULE message``."""

    __slots__ = ("path", "line", "col", "rule", "message")

    def __init__(self, path, line, col, rule, message):
        self.path = path
        self.line = line
        self.col = col
        self.rule = rule
        self.message = message

    @property
    def slug(self):
        return RULES[self.rule][0]

    @property
    def severity(self):
        return SEVERITY.get(self.rule, "error")

    def __repr__(self):
        return "Finding(%s:%s:%s %s)" % (self.path, self.line, self.col,
                                         self.rule)

    def __str__(self):
        return "%s:%d:%d: %s [%s] %s" % (
            self.path, self.line, self.col, self.rule, self.slug,
            self.message)

    def as_dict(self):
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "slug": self.slug,
                "severity": self.severity, "message": self.message}
