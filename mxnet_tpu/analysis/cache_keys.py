"""CS8xx — compile-cache key hygiene: op attrs that fragment the cache.

Every imperative op call is dispatched through ``ops.registry._push_op``,
and the op's keyword attrs become part of the jit cache key (and, since
the persistent compilation cache, part of the cross-process disk key).
An attr value that hashes by identity or not at all silently turns the
executable cache into a per-call-site miss machine:

* ``CS801`` — unhashable attr value: a set literal/comprehension or a
  fresh ``np``/``jnp``/``nd`` array constructed in the call.  Sets raise
  ``TypeError`` at key time; a fresh array object per call keys by
  identity, so EVERY call is a cache miss that recompiles (and never
  hits the persistent disk cache).
* ``CS802`` — identity-keyed attr: a ``lambda`` passed as an attr.
  Each evaluation of the call site mints a new function object → new
  key → retrace, even though the behaviour is identical.
* ``CS803`` — unfrozen dict attr: a dict literal/comprehension as an
  attr value.  Dicts are unhashable; freeze to a sorted tuple of items
  (``tuple(sorted(d.items()))``) before it reaches ``_jitted``.
* ``CS804`` — explicit ``None`` attr (advisory, ``--strict``): passing
  ``attr=None`` still enters the cache key, so the call site compiles a
  SEPARATE executable from an otherwise-identical site that omits the
  attr.  Drop the keyword to share the entry.

Heuristic: keyword arguments of op invocations — calls through ``F.<op>``
(the trace-time namespace), ``nd.<op>`` / ``sym.<op>`` / ``mx.nd.<op>``
(the eager/symbolic frontends), and direct ``_push_op(...)`` calls.
Positional args are data (traced by aval, not by value) and never flagged.
"""
from __future__ import annotations

import ast

from .findings import Finding

# roots whose attribute calls are op invocations with cache-keyed attrs
_OP_NAMESPACES = frozenset({"F", "nd", "sym"})
_ARRAY_ROOTS = frozenset({"np", "numpy", "jnp", "nd", "mx", "onp"})
_ARRAY_FUNCS = frozenset({"array", "asarray", "ones", "zeros", "full",
                          "arange", "empty"})


def _root_name(node):
    """Leftmost ``Name`` of an attribute chain (``mx.nd.op`` → ``mx``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _chain_attrs(node):
    """Attribute names of a chain, outermost last (``mx.nd.op`` →
    ``["nd", "op"]``)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    return parts[::-1]


def _is_op_call(call):
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in ("_push_op", "push_op")
    if not isinstance(fn, ast.Attribute):
        return False
    root = _root_name(fn)
    if root in _OP_NAMESPACES:
        return True
    # mx.nd.op / mx.sym.op: namespace appears inside the chain
    chain = _chain_attrs(fn)[:-1]  # drop the op name itself
    return root == "mx" and any(a in ("nd", "sym") for a in chain)


def _is_fresh_array_call(node):
    """``np.array(...)`` / ``jnp.asarray(...)`` / ``nd.ones(...)`` etc."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)):
        return False
    return (node.func.attr in _ARRAY_FUNCS
            and _root_name(node.func) in _ARRAY_ROOTS)


def _is_ctor_call(node, name):
    """``set(...)`` / ``dict(...)`` builtin-constructor call."""
    return (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == name)


class _CacheKeyChecker(ast.NodeVisitor):
    def __init__(self, path, findings, strict):
        self.path = path
        self.findings = findings
        self.strict = strict

    def _flag(self, node, rule, msg):
        self.findings.append(Finding(
            self.path, node.lineno, getattr(node, "col_offset", 0),
            rule, msg))

    def visit_Call(self, node):
        if _is_op_call(node):
            for kw in node.keywords:
                if kw.arg is None:  # **kwargs passthrough: opaque, skip
                    continue
                v = kw.value
                if isinstance(v, (ast.Set, ast.SetComp)) \
                        or _is_ctor_call(v, "set"):
                    self._flag(v, "CS801",
                               "op attr `%s` is a set literal — unhashable "
                               "in the executable cache key (TypeError at "
                               "dispatch); use a sorted tuple" % kw.arg)
                elif _is_fresh_array_call(v):
                    self._flag(v, "CS801",
                               "op attr `%s` constructs a fresh array per "
                               "call — keyed by object identity, every "
                               "call misses the executable cache and "
                               "recompiles (and can never hit the "
                               "persistent disk cache); pass data "
                               "positionally or hoist a hashable constant"
                               % kw.arg)
                elif isinstance(v, ast.Lambda):
                    self._flag(v, "CS802",
                               "op attr `%s` is a lambda — a new function "
                               "object (new cache key) per evaluation; "
                               "hoist it to a module-level def so the key "
                               "is stable" % kw.arg)
                elif isinstance(v, (ast.Dict, ast.DictComp)) \
                        or _is_ctor_call(v, "dict"):
                    self._flag(v, "CS803",
                               "op attr `%s` is a dict — unhashable in "
                               "the executable cache key; freeze to "
                               "tuple(sorted(d.items()))" % kw.arg)
                elif (self.strict and isinstance(v, ast.Constant)
                      and v.value is None):
                    self._flag(v, "CS804",
                               "op attr `%s=None` still enters the cache "
                               "key — this call site compiles a separate "
                               "executable from one that omits the attr; "
                               "drop the keyword to share the entry"
                               % kw.arg)
        self.generic_visit(node)


def run(path, tree, findings=None, strict=False):
    """Run the CS pass over one parsed module; returns the findings list."""
    if findings is None:
        findings = []
    _CacheKeyChecker(path, findings, strict).visit(tree)
    return findings
