"""Base utilities: errors, string constants, small helpers.

TPU-native re-imagination of the reference's ``python/mxnet/base.py`` and the
C-ABI error plumbing (``src/c_api/c_api_error.cc``).  There is no C ABI here:
the frontend talks straight to the in-process runtime (JAX/XLA), so errors are
ordinary Python exceptions rather than ``MXGetLastError`` strings.
"""
from __future__ import annotations


class MXNetError(RuntimeError):
    """Default error thrown by the runtime (parity: include/mxnet/c_api.h error path)."""


class NotSupportedForTPU(MXNetError):
    """Raised for reference features that cannot map to XLA semantics."""


_GRAD_REQ_MAP = {"null": 0, "write": 1, "add": 3}


def string_types():
    return (str,)


def check_call(ret):  # pragma: no cover - compat shim, no C calls exist
    """Parity shim: reference checks C-API return codes; we have none."""
    return ret


def py_str(x):
    if isinstance(x, bytes):
        return x.decode("utf-8")
    return str(x)


class classproperty:
    def __init__(self, fget):
        self.fget = fget

    def __get__(self, obj, owner):
        return self.fget(owner)
