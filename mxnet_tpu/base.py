"""Base utilities: errors, string constants, small helpers.

TPU-native re-imagination of the reference's ``python/mxnet/base.py`` and the
C-ABI error plumbing (``src/c_api/c_api_error.cc``).  There is no C ABI here:
the frontend talks straight to the in-process runtime (JAX/XLA), so errors are
ordinary Python exceptions rather than ``MXGetLastError`` strings.
"""
from __future__ import annotations

import os as _os
from contextlib import contextmanager as _contextmanager


class MXNetError(RuntimeError):
    """Default error thrown by the runtime (parity: include/mxnet/c_api.h error path)."""


class NotSupportedForTPU(MXNetError):
    """Raised for reference features that cannot map to XLA semantics."""


_GRAD_REQ_MAP = {"null": 0, "write": 1, "add": 3}


def string_types():
    return (str,)


def check_call(ret):  # pragma: no cover - compat shim, no C calls exist
    """Parity shim: reference checks C-API return codes; we have none."""
    return ret


def py_str(x):
    if isinstance(x, bytes):
        return x.decode("utf-8")
    return str(x)


class classproperty:
    def __init__(self, fget):
        self.fget = fget

    def __get__(self, obj, owner):
        return self.fget(owner)


def env_flag(name, default=False):
    """Boolean env knob: unset → ``default``; set → false only for
    ``""`` and ``"0"`` (the convention every ``MXNET_*`` switch in this
    repo follows, so ``MXNET_TELEMETRY=0`` and ``MXNET_TELEMETRY=``
    both disable while any other value enables)."""
    v = _os.environ.get(name)
    if v is None:
        return bool(default)
    return v not in ("", "0")


@_contextmanager
def atomic_path(fname):
    """Write-then-rename: yield a temp path in ``fname``'s directory; on
    clean exit ``os.replace`` it over ``fname``, on error unlink it.

    Every checkpoint writer (``nd.save``, ``save_checkpoint``,
    ``Trainer.save_states``, ``Block.save_parameters``) goes through
    this, so a preemption mid-write can never leave a torn file where a
    loadable checkpoint used to be — the previous checkpoint survives
    untouched until the new bytes are fully on disk (same-directory
    rename keeps the replace atomic on POSIX; cross-device tmp dirs
    would silently degrade it to copy+delete).
    """
    fname = _os.fspath(fname)
    d, base = _os.path.split(_os.path.abspath(fname))
    tmp = _os.path.join(d, ".%s.tmp.%d" % (base, _os.getpid()))
    # lazy: base is imported by everything, testing.rescheck imports base
    from .testing import rescheck as _rescheck
    tok = _rescheck.acquire("tempfile", tmp)
    try:
        try:
            yield tmp
            _os.replace(tmp, fname)
        except BaseException:
            try:
                _os.unlink(tmp)
            except OSError:
                pass
            raise
    finally:
        _rescheck.release(tok)
