"""Image read/augment utilities.

Capability parity with the reference's ``python/mxnet/image/image.py``
(imdecode, imresize, crops, color_normalize, the ``Augmenter`` class
hierarchy, ``CreateAugmenter:1089``, ``ImageIter:1178``) whose heavy ops run
through OpenCV on GPU-adjacent hosts.  TPU-native stance: augmentation is
host-side NumPy feeding the device pipeline (the TPU never decodes JPEGs);
arrays are HWC uint8/float32 like the reference.
"""
from __future__ import annotations

import os
import random as pyrandom

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .. import io as _io
from .. import recordio


def _to_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer → HWC NDArray (parity: image.py:imdecode).

    Accepts .npy payloads natively; JPEG/PNG via PIL when importable.
    """
    arr = recordio._decode_image_bytes(bytes(buf))
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if flag == 0 and arr.shape[2] == 3:
        arr = arr.mean(axis=2, keepdims=True).astype(arr.dtype)
    return nd.array(arr)


def imread(filename, flag=1, to_rgb=True):
    """Read an image file (parity: image.py:imread)."""
    if filename.endswith('.npy'):
        arr = np.load(filename)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return nd.array(arr)
    with open(filename, 'rb') as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src, w, h, interp=1):
    """Resize HWC image to (h, w) (parity: image.py:imresize).

    Nearest/bilinear on host NumPy (interp 0/1; other codes fall back to
    bilinear — OpenCV's exotic modes are out of scope).
    """
    arr = _to_np(src).astype(np.float32)
    ih, iw = arr.shape[:2]
    if interp == 0:
        yy = np.clip((np.arange(h) * ih / float(h)).astype(int), 0, ih - 1)
        xx = np.clip((np.arange(w) * iw / float(w)).astype(int), 0, iw - 1)
        out = arr[yy][:, xx]
    else:
        y = (np.arange(h) + 0.5) * ih / float(h) - 0.5
        x = (np.arange(w) + 0.5) * iw / float(w) - 0.5
        y0 = np.clip(np.floor(y).astype(int), 0, ih - 1)
        x0 = np.clip(np.floor(x).astype(int), 0, iw - 1)
        y1 = np.clip(y0 + 1, 0, ih - 1)
        x1 = np.clip(x0 + 1, 0, iw - 1)
        wy = np.clip(y - y0, 0, 1)[:, None, None]
        wx = np.clip(x - x0, 0, 1)[None, :, None]
        out = (arr[y0][:, x0] * (1 - wy) * (1 - wx) +
               arr[y1][:, x0] * wy * (1 - wx) +
               arr[y0][:, x1] * (1 - wy) * wx +
               arr[y1][:, x1] * wy * wx)
    if _to_np(src).dtype == np.uint8:
        out = np.clip(np.rint(out), 0, 255).astype(np.uint8)
    return nd.array(out)


def scale_down(src_size, size):
    """Scale size down to fit in src_size (parity: image.py:scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge is ``size`` (parity: image.py:resize_short)."""
    h, w = _to_np(src).shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = _to_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(nd.array(out), size[0], size[1], interp=interp)
    return nd.array(out)


def random_crop(src, size, interp=2):
    h, w = _to_np(src).shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = _to_np(src).shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    h, w = _to_np(src).shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    src = src if isinstance(src, NDArray) else nd.array(src)
    src = nd.cast(src, 'float32')
    mean = mean if isinstance(mean, NDArray) or mean is None \
        else nd.array(np.asarray(mean, dtype=np.float32))
    std = std if isinstance(std, NDArray) or std is None \
        else nd.array(np.asarray(std, dtype=np.float32))
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


# ---------------------------------------------------------------------------
# Augmenters (parity: image.py Augmenter:662 hierarchy)
# ---------------------------------------------------------------------------
class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):  # pragma: no cover - abstract
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd.cast(src, 'float32') * alpha


class ContrastJitterAug(Augmenter):
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        arr = _to_np(src).astype(np.float32)
        gray = (arr * self.coef).sum() * 3.0 / arr.size
        return nd.array(arr * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        arr = _to_np(src).astype(np.float32)
        gray = (arr * self.coef).sum(axis=2, keepdims=True)
        return nd.array(arr * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    tyiq = np.array([[0.299, 0.587, 0.114],
                     [0.596, -0.274, -0.321],
                     [0.211, -0.523, 0.311]], dtype=np.float32)
    ityiq = np.array([[1.0, 0.956, 0.621],
                      [1.0, -0.272, -0.647],
                      [1.0, -1.107, 1.705]], dtype=np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      dtype=np.float32)
        t = self.ityiq @ bt @ self.tyiq
        arr = _to_np(src).astype(np.float32)
        return nd.array(arr @ t.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, dtype=np.float32)
        self.eigvec = np.asarray(eigvec, dtype=np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,)).astype(
            np.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return nd.cast(src, 'float32') + nd.array(
            rgb.reshape(1, 1, 3).astype(np.float32))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    mat = np.array([[0.21, 0.21, 0.21],
                    [0.72, 0.72, 0.72],
                    [0.07, 0.07, 0.07]], dtype=np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = _to_np(src).astype(np.float32)
            return nd.array(arr @ self.mat)
        return src if isinstance(src, NDArray) else nd.array(src)


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd.flip(src if isinstance(src, NDArray)
                           else nd.array(src), axis=1)
        return src if isinstance(src, NDArray) else nd.array(src)


class CastAug(Augmenter):
    def __init__(self, typ='float32'):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return nd.cast(src if isinstance(src, NDArray) else nd.array(src),
                       self.typ)


# ImageNet preprocessing constants (single source for cls + detection)
IMAGENET_MEAN = np.array([123.68, 116.28, 103.53])
IMAGENET_STD = np.array([58.395, 57.12, 57.375])
PCA_EIGVAL = np.array([55.46, 4.794, 1.148])
PCA_EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                       [-0.5808, -0.0045, -0.8140],
                       [-0.5836, -0.6948, 0.4203]])


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter list (parity: image.py CreateAugmenter:1089)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(pca_noise, PCA_EIGVAL, PCA_EIGVEC))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = IMAGENET_MEAN
    elif mean is not None:
        mean = np.asarray(mean)
        assert mean.shape[0] in [1, 3]
    if std is True:
        std = IMAGENET_STD
    elif std is not None:
        std = np.asarray(std)
        assert std.shape[0] in [1, 3]
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(_io.DataIter):
    """Image iterator over .rec or .lst+dir with augmenters
    (parity: image.py ImageIter:1178)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 shuffle=False, aug_list=None, imglist=None,
                 data_name='data', label_name='softmax_label', **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or imglist is not None
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ('resize', 'rand_crop', 'rand_resize',
                         'rand_mirror', 'mean', 'std', 'brightness',
                         'contrast', 'saturation', 'hue', 'pca_noise',
                         'rand_gray', 'inter_method')})
        self.imgrec = None
        self.seq = None
        self.imglist = None
        self.path_root = path_root
        if path_imgrec:
            idx_path = os.path.splitext(path_imgrec)[0] + '.idx'
            if os.path.exists(idx_path):
                self.imgrec = recordio.MXIndexedRecordIO(
                    idx_path, path_imgrec, 'r')
                self.seq = list(self.imgrec.keys)
            else:
                records = list(recordio.RecordIOIterable(path_imgrec))
                self.imglist = {
                    i: recordio.unpack(r) for i, r in enumerate(records)}
                self.seq = list(range(len(records)))
        elif path_imglist:
            self.imglist = {}
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split('\t')
                    label = np.array(
                        [float(x) for x in parts[1:-1]], dtype=np.float32)
                    self.imglist[int(parts[0])] = (
                        recordio.IRHeader(0, label if len(label) > 1
                                          else float(label[0]),
                                          int(parts[0]), 0),
                        parts[-1])
            self.seq = sorted(self.imglist)
        else:
            self.imglist = {}
            for i, (label, fname) in enumerate(imglist):
                self.imglist[i] = (
                    recordio.IRHeader(0, label, i, 0), fname)
            self.seq = list(range(len(imglist)))
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [_io.DataDesc('data', (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [_io.DataDesc('softmax_label', shape)]

    def reset(self):
        if self.shuffle:
            pyrandom.shuffle(self.seq)
        self.cur = 0

    def next_sample(self):
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.imgrec is not None:
            header, img = recordio.unpack(self.imgrec.read_idx(idx))
            return header.label, img
        header, payload = self.imglist[idx]
        if isinstance(payload, str):
            path = payload if self.path_root is None else \
                os.path.join(self.path_root, payload)
            return header.label, imread(path)
        return header.label, payload

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
        batch_label = np.zeros((self.batch_size, self.label_width),
                               dtype=np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, img = self.next_sample()
                if isinstance(img, (bytes, bytearray)):
                    img = imdecode(img)
                elif not isinstance(img, NDArray):
                    img = nd.array(np.asarray(img))
                for aug in self.auglist:
                    img = aug(img)
                arr = _to_np(img).astype(np.float32)
                if arr.shape[:2] != (h, w):
                    arr = _to_np(imresize(nd.array(arr), w, h))
                batch_data[i] = arr.transpose(2, 0, 1)
                batch_label[i] = np.atleast_1d(
                    np.asarray(label, dtype=np.float32))[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        label_out = batch_label[:, 0] if self.label_width == 1 \
            else batch_label
        return _io.DataBatch(
            data=[nd.array(batch_data)], label=[nd.array(label_out)],
            pad=self.batch_size - i)
