"""Detection augmenters + iterator (parity: python/mxnet/image/detection.py).

Labels are (num_object, 5+) float arrays per image — rows of
``[class_id, xmin, ymin, xmax, ymax]`` with coordinates normalized to
[0, 1] — padded with -1 rows to the batch-wide ``max_objects``
(reference ImageDetIter label padding semantics).
"""
from __future__ import annotations

import random as pyrandom

import numpy as np

from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .image import (
    Augmenter, CreateAugmenter, ImageIter, _to_np, imdecode, imresize,
    ResizeAug, ForceResizeAug, ColorNormalizeAug, CastAug,
    BrightnessJitterAug, ContrastJitterAug, SaturationJitterAug,
    HueJitterAug, RandomGrayAug, LightingAug,
    IMAGENET_MEAN, IMAGENET_STD, PCA_EIGVAL, PCA_EIGVEC,
)


class DetAugmenter:
    """Base detection augmenter: ``(img, label) -> (img, label)``."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter; label passes through (ref :62)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one (or none) of several augmenters (ref :80)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and box x-coordinates together (ref :109)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            arr = _to_np(src)[:, ::-1, :].copy()
            src = nd.array(arr)
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            x2 = label[valid, 3].copy()
            label[valid, 1] = 1.0 - x2
            label[valid, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (SSD-style; ref :135).

    Samples a crop whose coverage of at least one box exceeds
    ``min_object_covered``; boxes are clipped to the crop and dropped
    when their center falls outside.
    """

    def __init__(self, min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.3, 1.0), max_attempts=25):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _coverage(self, crop, boxes):
        cx1, cy1, cx2, cy2 = crop
        ix1 = np.maximum(boxes[:, 0], cx1)
        iy1 = np.maximum(boxes[:, 1], cy1)
        ix2 = np.minimum(boxes[:, 2], cx2)
        iy2 = np.minimum(boxes[:, 3], cy2)
        inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
        area = np.maximum(
            (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1]),
            1e-12)
        return inter / area

    def __call__(self, src, label):
        h, w = _to_np(src).shape[:2]
        valid = label[:, 0] >= 0
        boxes = label[valid, 1:5]
        if boxes.size == 0:
            return src, label
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, np.sqrt(area * ratio))
            ch = min(1.0, np.sqrt(area / ratio))
            cx = pyrandom.uniform(0, 1 - cw)
            cy = pyrandom.uniform(0, 1 - ch)
            crop = (cx, cy, cx + cw, cy + ch)
            cov = self._coverage(crop, boxes)
            if cov.max() < self.min_object_covered:
                continue
            # keep boxes whose center is inside the crop
            centers_x = (boxes[:, 0] + boxes[:, 2]) / 2
            centers_y = (boxes[:, 1] + boxes[:, 3]) / 2
            keep = ((centers_x > crop[0]) & (centers_x < crop[2])
                    & (centers_y > crop[1]) & (centers_y < crop[3]))
            if not keep.any():
                continue
            arr = _to_np(src)
            x0, y0 = int(cx * w), int(cy * h)
            x1, y1 = int((cx + cw) * w), int((cy + ch) * h)
            src = nd.array(arr[y0:y1, x0:x1, :].copy())
            new_label = np.full_like(label, -1.0)
            kept = label[valid][keep].copy()
            kept[:, 1] = np.clip((kept[:, 1] - crop[0]) / cw, 0, 1)
            kept[:, 2] = np.clip((kept[:, 2] - crop[1]) / ch, 0, 1)
            kept[:, 3] = np.clip((kept[:, 3] - crop[0]) / cw, 0, 1)
            kept[:, 4] = np.clip((kept[:, 4] - crop[1]) / ch, 0, 1)
            new_label[:kept.shape[0]] = kept
            return src, new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Expand the canvas and place the image randomly (zoom-out; ref :344)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=25,
                 pad_val=(127, 127, 127)):
        self.area_range = area_range
        self.aspect_ratio_range = aspect_ratio_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def _sample_canvas(self, w, h):
        for _ in range(self.max_attempts):
            scale = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            nw = int(w * np.sqrt(scale * ratio))
            nh = int(h * np.sqrt(scale / ratio))
            if nw >= w and nh >= h and (nw > w or nh > h):
                return nw, nh
        return w, h

    def __call__(self, src, label):
        arr = _to_np(src)
        h, w = arr.shape[:2]
        nw, nh = self._sample_canvas(w, h)
        if (nw, nh) == (w, h):
            return src, label
        canvas = np.empty((nh, nw, arr.shape[2]), arr.dtype)
        canvas[:] = np.asarray(self.pad_val, arr.dtype)
        x0 = pyrandom.randint(0, nw - w)
        y0 = pyrandom.randint(0, nh - h)
        canvas[y0:y0 + h, x0:x0 + w, :] = arr
        label = label.copy()
        valid = label[:, 0] >= 0
        label[valid, 1] = (label[valid, 1] * w + x0) / nw
        label[valid, 3] = (label[valid, 3] * w + x0) / nw
        label[valid, 2] = (label[valid, 2] * h + y0) / nh
        label[valid, 4] = (label[valid, 4] * h + y0) / nh
        return nd.array(canvas), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0., rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Build the standard detection pipeline (ref :685)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (min(1.0, area_range[0]),
                                 min(1.0, area_range[1])), max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    for prob, cls in ((brightness, BrightnessJitterAug),
                      (contrast, ContrastJitterAug),
                      (saturation, SaturationJitterAug),
                      (hue, HueJitterAug)):
        if prob > 0:
            auglist.append(DetBorrowAug(cls(prob)))
    if pca_noise > 0:
        auglist.append(DetBorrowAug(
            LightingAug(pca_noise, PCA_EIGVAL, PCA_EIGVEC)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    # same defaulting rules as CreateAugmenter: only `True` pulls in the
    # ImageNet constant — passing just std must NOT imply a mean shift
    if mean is True:
        mean = IMAGENET_MEAN
    elif mean is not None:
        mean = np.asarray(mean)
    if std is True:
        std = IMAGENET_STD
    elif std is not None:
        std = np.asarray(std)
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(CastAug()))
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: images + padded (max_objects, 5) labels
    (parity: detection.py ImageDetIter:780)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, shuffle=False,
                 aug_list=None, imglist=None, max_objects=None,
                 object_width=None, **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_pad", "rand_gray",
                         "rand_mirror", "mean", "std", "brightness",
                         "contrast", "saturation", "pca_noise", "hue",
                         "inter_method", "min_object_covered",
                         "aspect_ratio_range", "area_range",
                         "max_attempts", "pad_val")})
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle,
                         aug_list=[], imglist=imglist)
        self.det_auglist = aug_list
        # flat labels have no intrinsic width; default 5 unless told
        self.object_width = object_width or 5
        if max_objects is None:
            # full scan: both the padded object count and the label width
            scanned_max = 1
            for idx in self.seq:
                lbl = self._label_of(idx)
                scanned_max = max(scanned_max, lbl.shape[0])
                self.object_width = max(self.object_width, lbl.shape[1])
            max_objects = scanned_max
        elif object_width is None and self.seq:
            # max_objects given: stay O(1) — infer the width from the
            # first label only (2-D labels wider than 5 would otherwise
            # be reshaped to garbage); pass object_width explicitly for
            # mixed-width datasets
            lbl = self._label_of(self.seq[0])
            self.object_width = max(self.object_width, lbl.shape[1])
        self.max_objects = max_objects

    def _label_of(self, idx):
        if self.imgrec is not None:
            from .. import recordio

            header, _ = recordio.unpack(self.imgrec.read_idx(idx))
            lbl = np.asarray(header.label, np.float32)
        else:
            lbl = np.asarray(self.imglist[idx][0].label, np.float32)
        return lbl.reshape(-1, self.object_width) if lbl.ndim == 1 else lbl

    @property
    def provide_label(self):
        from .. import io as _io

        return [_io.DataDesc(
            "label",
            (self.batch_size, self.max_objects, self.object_width))]

    def next(self):
        from .. import io as _io

        c, h, w = self.data_shape
        ow = self.object_width
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = np.full(
            (self.batch_size, self.max_objects, ow), -1.0, np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, img = self.next_sample()
                label = np.asarray(label, np.float32)
                label = label.reshape(-1, ow) if label.ndim == 1 else label
                padded = np.full((self.max_objects, ow), -1.0, np.float32)
                clipped = label[:self.max_objects, :ow]
                # narrower labels right-pad with -1 instead of failing to
                # broadcast into the (max_objects, ow) buffer
                padded[:clipped.shape[0], :clipped.shape[1]] = clipped
                if isinstance(img, (bytes, bytearray)):
                    img = imdecode(img)
                elif not isinstance(img, NDArray):
                    img = nd.array(np.asarray(img))
                for aug in self.det_auglist:
                    img, padded = aug(img, padded)
                arr = _to_np(img).astype(np.float32)
                if arr.shape[:2] != (h, w):
                    arr = _to_np(imresize(nd.array(arr), w, h))
                batch_data[i] = arr.transpose(2, 0, 1)
                batch_label[i] = padded
                i += 1
        except StopIteration:
            if i == 0:
                raise
        return _io.DataBatch(
            data=[nd.array(batch_data)], label=[nd.array(batch_label)],
            pad=self.batch_size - i)
