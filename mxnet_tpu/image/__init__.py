"""Image API (parity: ``python/mxnet/image/``)."""
from .image import (  # noqa: F401
    imdecode, imread, imresize, resize_short, fixed_crop, center_crop,
    random_crop, random_size_crop, color_normalize, scale_down,
    Augmenter, SequentialAug, RandomOrderAug, ResizeAug, ForceResizeAug,
    RandomCropAug, RandomSizedCropAug, CenterCropAug, BrightnessJitterAug,
    ContrastJitterAug, SaturationJitterAug, HueJitterAug, ColorJitterAug,
    LightingAug, ColorNormalizeAug, RandomGrayAug, HorizontalFlipAug,
    CastAug, CreateAugmenter, ImageIter,
)
from .detection import (  # noqa: F401
    DetAugmenter, DetBorrowAug, DetRandomSelectAug, DetHorizontalFlipAug,
    DetRandomCropAug, DetRandomPadAug, CreateDetAugmenter, ImageDetIter,
)
