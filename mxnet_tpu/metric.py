"""Online evaluation metrics.

Capability parity with the reference's ``python/mxnet/metric.py``
(``EvalMetric:68``, registry ``:40``, Accuracy:438, TopKAccuracy:511,
F1:745, MCC:839, Perplexity:954, MAE/MSE/RMSE:1078-1207, CrossEntropy:1272,
PearsonCorrelation:1416, Loss, Custom, CompositeEvalMetric:301).

Sync discipline (this file is a permanent ``tools/mxlint.py`` target): the
hot per-batch metrics (``Accuracy``, ``Loss``) reduce ON DEVICE in
``update()`` — a tiny async reduction enqueued on the PJRT stream, zero
host pulls per batch — and queue the resulting scalar; ``get()`` sums the
queue and pulls ONCE (the single intentional sync, marked
``# mxlint: allow-host-sync``).  The branchy long-tail metrics (F1, MCC,
PCC, ...) still pull per ``update()``: their math is host-shaped and they
run per-epoch, not per-batch — that trade is intentional and recorded in
``tools/mxlint_suppressions.txt``.
"""
from __future__ import annotations

import math

import numpy

from . import registry
from .base import MXNetError  # noqa: F401 (re-export parity)

__all__ = [
    'EvalMetric', 'CompositeEvalMetric', 'Accuracy', 'TopKAccuracy',
    'F1', 'MCC', 'Perplexity', 'MAE', 'MSE', 'RMSE', 'CrossEntropy',
    'NegativeLogLikelihood', 'PearsonCorrelation', 'PCC', 'Loss', 'Torch',
    'Caffe', 'CustomMetric', 'np', 'create', 'register', 'get',
]


def _as_numpy(x):
    if hasattr(x, 'asnumpy'):
        return x.asnumpy()
    return numpy.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """Parity: metric.py check_label_shapes — validate label/pred pairing."""
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels {} does not match shape of predictions {}"
            .format(label_shape, pred_shape))
    if wrap:
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
    return labels, preds


class EvalMetric:
    """Base class for all evaluation metrics (parity: metric.py:68)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        # device-side accumulation queue: (scalar device array, host count)
        # pairs appended by update(), drained by ONE pull in get()
        self._pending = []
        self._pending_inst = 0
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = dict(self._kwargs)
        config.update({
            'metric': self.__class__.__name__,
            'name': self.name,
            'output_names': self.output_names,
            'label_names': self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):  # pragma: no cover - abstract
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0
        self._pending = []
        self._pending_inst = 0

    def reset_local(self):
        # flush first: queued device sums predate the reset and must still
        # land in the *global* accumulators
        self._flush()
        self.num_inst = 0
        self.sum_metric = 0.0

    def _device_accumulate(self, value, n):
        """Queue a device-side partial sum (no sync): ``value`` is a scalar
        device array, ``n`` the instance count (host metadata)."""
        self._pending.append(value)
        self._pending_inst += int(n)

    def _flush(self):
        """Drain the device queue with ONE host pull."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        n, self._pending_inst = self._pending_inst, 0
        total = pending[0]
        for v in pending[1:]:
            total = total + v  # device-side adds, still async
        total = float(total)  # mxlint: allow-host-sync (the one pull)
        self.sum_metric += total
        self.global_sum_metric += total
        self.num_inst += n
        self.global_num_inst += n

    def get(self):
        self._flush()
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        self._flush()
        if self.global_num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, self.global_sum_metric / self.global_num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_global_name_value(self):
        name, value = self.get_global()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


# the metric registry (parity: metric.py:40-66 register/create/get)
register = registry.get_register_func(EvalMetric, 'metric')
alias = registry.get_alias_func(EvalMetric, 'metric')
_create = registry.get_create_func(EvalMetric, 'metric')


def create(metric, *args, **kwargs):
    """Create a metric from name / callable / list of names."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _create(metric, *args, **kwargs)


def get(name, *args, **kwargs):
    return create(name, *args, **kwargs)


@register
@alias('composite')
class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (parity: metric.py:301)."""

    def __init__(self, metrics=None, name='composite',
                 output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)
        if metrics is None:
            metrics = []
        self.metrics = [create(m) for m in metrics]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            raise ValueError("Metric index {} is out of range 0..{}".format(
                index, len(self.metrics)))

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, 'metrics', []):
            metric.reset()

    def reset_local(self):
        for metric in getattr(self, 'metrics', []):
            metric.reset_local()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_global(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get_global()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


@register
@alias('acc')
class Accuracy(EvalMetric):
    """Classification accuracy (parity: metric.py:438)."""

    def __init__(self, axis=1, name='accuracy',
                 output_names=None, label_names=None):
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            if hasattr(pred, 'data') and hasattr(label, 'data'):
                # device path: enqueue the reduction, pull nothing — the
                # correct-count lands in the pending queue and is pulled
                # once per get() (see module docstring)
                pd, ld = pred.data(), label.data()
                if pd.ndim > ld.ndim:
                    pd = pd.argmax(axis=self.axis)
                correct = (pd.astype('int32').ravel()
                           == ld.astype('int32').ravel()).sum()
                self._device_accumulate(correct, ld.size)
                continue
            pred, label = _as_numpy(pred), _as_numpy(label)
            if pred.ndim > label.ndim:
                pred = numpy.argmax(pred, axis=self.axis)
            pred = pred.astype('int32').flat
            label = label.astype('int32').flat
            check_label_shapes(label, pred)
            correct = (numpy.asarray(pred) == numpy.asarray(label)).sum()
            self.sum_metric += correct
            self.global_sum_metric += correct
            self.num_inst += len(numpy.asarray(label))
            self.global_num_inst += len(numpy.asarray(label))


@register
@alias('top_k_accuracy', 'top_k_acc')
class TopKAccuracy(EvalMetric):
    """Top-k accuracy (parity: metric.py:511)."""

    def __init__(self, top_k=1, name='top_k_accuracy',
                 output_names=None, label_names=None):
        super().__init__(name, top_k=top_k, output_names=output_names,
                         label_names=label_names)
        self.top_k = top_k
        if self.top_k <= 1:
            raise ValueError("Use Accuracy for top_k=1")
        self.name += '_%d' % self.top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            pred, label = _as_numpy(pred), _as_numpy(label)
            assert pred.ndim == 2, 'Predictions should be 2 dims'
            # argpartition is O(n) vs argsort O(n log n): same trick as ref
            index = numpy.argpartition(pred.astype('float32'),
                                       -self.top_k)[:, -self.top_k:]
            label = label.astype('int32')
            num_samples = pred.shape[0]
            hits = (index == label.reshape(-1, 1)).any(axis=1).sum()
            self.sum_metric += hits
            self.global_sum_metric += hits
            self.num_inst += num_samples
            self.global_num_inst += num_samples


class _BinaryClassificationStats:
    """Accumulated TP/FP/TN/FN (parity: metric.py _BinaryClassificationMetrics)."""

    def __init__(self):
        self.reset_stats()

    def reset_stats(self):
        self.true_positives = 0
        self.false_positives = 0
        self.true_negatives = 0
        self.false_negatives = 0
        self.global_true_positives = 0
        self.global_false_positives = 0
        self.global_true_negatives = 0
        self.global_false_negatives = 0

    def update_binary_stats(self, label, pred):
        pred = _as_numpy(pred)
        label = _as_numpy(label).astype('int32')
        pred_label = numpy.argmax(pred, axis=1) if pred.ndim > 1 else \
            (pred > 0.5).astype('int32')
        check_label_shapes(label.flat, pred_label.flat)
        if len(numpy.unique(label)) > 2:
            raise ValueError("%s currently only supports binary"
                             " classification." % self.__class__.__name__)
        pred_true = pred_label == 1
        pred_false = ~pred_true
        label_true = label.reshape(pred_label.shape) == 1
        label_false = ~label_true
        tp = (pred_true & label_true).sum()
        fp = (pred_true & label_false).sum()
        fn = (pred_false & label_true).sum()
        tn = (pred_false & label_false).sum()
        self.true_positives += tp
        self.false_positives += fp
        self.false_negatives += fn
        self.true_negatives += tn
        self.global_true_positives += tp
        self.global_false_positives += fp
        self.global_false_negatives += fn
        self.global_true_negatives += tn

    @property
    def precision(self):
        denom = self.true_positives + self.false_positives
        return self.true_positives / denom if denom > 0 else 0.

    @property
    def recall(self):
        denom = self.true_positives + self.false_negatives
        return self.true_positives / denom if denom > 0 else 0.

    @property
    def fscore(self):
        if self.precision + self.recall > 0:
            return 2 * self.precision * self.recall / \
                (self.precision + self.recall)
        return 0.

    @property
    def global_fscore(self):
        gp = self.global_true_positives + self.global_false_positives
        gr = self.global_true_positives + self.global_false_negatives
        precision = self.global_true_positives / gp if gp > 0 else 0.
        recall = self.global_true_positives / gr if gr > 0 else 0.
        if precision + recall > 0:
            return 2 * precision * recall / (precision + recall)
        return 0.

    def matthewscc(self, use_global=False):
        if use_global:
            tp, fp = self.global_true_positives, self.global_false_positives
            tn, fn = self.global_true_negatives, self.global_false_negatives
        else:
            tp, fp = self.true_positives, self.false_positives
            tn, fn = self.true_negatives, self.false_negatives
        if not tp + fp or not tp + fn or not tn + fp or not tn + fn:
            return 0.
        terms = [tp + fp, tp + fn, tn + fp, tn + fn]
        denom = 1.
        for t in terms:
            denom *= t
        return (tp * tn - fp * fn) / math.sqrt(denom)

    @property
    def total_examples(self):
        return (self.false_negatives + self.false_positives +
                self.true_negatives + self.true_positives)

    @property
    def global_total_examples(self):
        return (self.global_false_negatives + self.global_false_positives +
                self.global_true_negatives + self.global_true_positives)


@register
class F1(EvalMetric):
    """Binary F1 score (parity: metric.py:745)."""

    def __init__(self, name='f1', output_names=None, label_names=None,
                 average='macro'):
        self.average = average
        self.metrics = _BinaryClassificationStats()
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(label, pred)
        if self.average == 'macro':
            self.sum_metric += self.metrics.fscore
            self.global_sum_metric += self.metrics.global_fscore
            self.num_inst += 1
            self.global_num_inst += 1
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.fscore * \
                self.metrics.total_examples
            self.global_sum_metric = self.metrics.global_fscore * \
                self.metrics.global_total_examples
            self.num_inst = self.metrics.total_examples
            self.global_num_inst = self.metrics.global_total_examples

    def reset(self):
        self.sum_metric = 0.
        self.num_inst = 0.
        self.global_sum_metric = 0.
        self.global_num_inst = 0.
        getattr(self, 'metrics', _BinaryClassificationStats()).reset_stats()

    def reset_local(self):
        self.sum_metric = 0.
        self.num_inst = 0.
        self.metrics.reset_stats()


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (parity: metric.py:839)."""

    def __init__(self, name='mcc', output_names=None, label_names=None,
                 average='macro'):
        self._average = average
        self._metrics = _BinaryClassificationStats()
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self._metrics.update_binary_stats(label, pred)
        if self._average == 'macro':
            self.sum_metric += self._metrics.matthewscc()
            self.global_sum_metric += self._metrics.matthewscc(
                use_global=True)
            self.num_inst += 1
            self.global_num_inst += 1
            self._metrics.reset_stats()
        else:
            self.sum_metric = self._metrics.matthewscc() * \
                self._metrics.total_examples
            self.global_sum_metric = self._metrics.matthewscc(True) * \
                self._metrics.global_total_examples
            self.num_inst = self._metrics.total_examples
            self.global_num_inst = self._metrics.global_total_examples

    def reset(self):
        self.sum_metric = 0.
        self.num_inst = 0.
        self.global_sum_metric = 0.
        self.global_num_inst = 0.
        getattr(self, '_metrics', _BinaryClassificationStats()).reset_stats()

    def reset_local(self):
        self.sum_metric = 0.
        self.num_inst = 0.
        self._metrics.reset_stats()


@register
class Perplexity(EvalMetric):
    """Perplexity (parity: metric.py:954)."""

    def __init__(self, ignore_label=None, axis=-1, name='perplexity',
                 output_names=None, label_names=None):
        super().__init__(name, ignore_label=ignore_label,
                         output_names=output_names, label_names=label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            label = label.reshape(-1).astype('int64')
            pred = pred.reshape(label.shape[0], -1)
            probs = pred[numpy.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = numpy.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += label.shape[0]
        self.sum_metric += loss
        self.global_sum_metric += loss
        self.num_inst += num
        self.global_num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, math.exp(self.sum_metric / self.num_inst))

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float('nan'))
        return (self.name,
                math.exp(self.global_sum_metric / self.global_num_inst))


@register
class MAE(EvalMetric):
    """Mean absolute error (parity: metric.py:1078)."""

    def __init__(self, name='mae', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            mae = numpy.abs(label - pred).mean()
            self.sum_metric += mae
            self.global_sum_metric += mae
            self.num_inst += 1
            self.global_num_inst += 1


@register
class MSE(EvalMetric):
    """Mean squared error (parity: metric.py:1139)."""

    def __init__(self, name='mse', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            mse = ((label - pred) ** 2.0).mean()
            self.sum_metric += mse
            self.global_sum_metric += mse
            self.num_inst += 1
            self.global_num_inst += 1


@register
class RMSE(MSE):
    """Root mean squared error (parity: metric.py:1207)."""

    def __init__(self, name='rmse', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float('nan'))
        return (self.name,
                math.sqrt(self.global_sum_metric / self.global_num_inst))


@register
@alias('ce')
class CrossEntropy(EvalMetric):
    """Cross-entropy of predicted distribution vs label (metric.py:1272)."""

    def __init__(self, eps=1e-12, name='cross-entropy',
                 output_names=None, label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), label.astype('int64')]
            loss = (-numpy.log(prob + self.eps)).sum()
            self.sum_metric += loss
            self.global_sum_metric += loss
            self.num_inst += label.shape[0]
            self.global_num_inst += label.shape[0]


@register
@alias('nll_loss')
class NegativeLogLikelihood(EvalMetric):
    """NLL over predicted probabilities (parity: metric.py:1344)."""

    def __init__(self, eps=1e-12, name='nll-loss',
                 output_names=None, label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            num_examples = pred.shape[0]
            assert label.shape[0] == num_examples
            prob = pred[numpy.arange(num_examples), label.astype('int64')]
            nll = (-numpy.log(prob + self.eps)).sum()
            self.sum_metric += nll
            self.global_sum_metric += nll
            self.num_inst += num_examples
            self.global_num_inst += num_examples


@register
@alias('pearsonr')
class PearsonCorrelation(EvalMetric):
    """Streaming Pearson correlation (parity: metric.py:1416).

    Uses running co-moment accumulation so the estimate is over ALL samples
    seen, not a mean of per-batch correlations.
    """

    def __init__(self, name='pearsonr', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def reset(self):
        self._sse_p = 0
        self._mean_p = 0
        self._sse_l = 0
        self._mean_l = 0
        self._pred_nums = 0
        self._label_nums = 0
        self._conv = 0
        super().reset()

    def update_variance(self, new_values, *aggregate):
        count, mean, m2 = aggregate
        count += len(new_values)
        delta = new_values - mean
        mean += numpy.sum(delta / count)
        delta2 = new_values - mean
        m2 += numpy.sum(delta * delta2)
        return count, mean, m2

    def update_cov(self, label, pred):
        self._conv += numpy.sum(
            (label - self._mean_l) * (pred - self._mean_p))

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel().astype('float64')
            pred = _as_numpy(pred).ravel().astype('float64')
            self._label_nums, self._mean_l, self._sse_l = \
                self.update_variance(label, self._label_nums, self._mean_l,
                                     self._sse_l)
            self.update_cov(label, pred)
            self._pred_nums, self._mean_p, self._sse_p = \
                self.update_variance(pred, self._pred_nums, self._mean_p,
                                     self._sse_p)

    def get(self):
        if self._sse_p == 0 or self._sse_l == 0:
            return (self.name, float('nan'))
        n = self._label_nums
        corr = self._conv / ((n - 1) * numpy.sqrt(self._sse_p / (n - 1)) *
                             numpy.sqrt(self._sse_l / (n - 1)))
        return (self.name, float(corr))


@register
class PCC(EvalMetric):
    """Multiclass Pearson correlation via confusion matrix (metric.py:1549)."""

    def __init__(self, name='pcc', output_names=None, label_names=None):
        self.k = 2
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names)

    def _grow(self, inc):
        self.lcm = numpy.pad(self.lcm, ((0, inc), (0, inc)), 'constant')
        self.gcm = numpy.pad(self.gcm, ((0, inc), (0, inc)), 'constant')
        self.k += inc

    @staticmethod
    def _calc_mcc(cmat):
        n = cmat.sum()
        x = cmat.sum(axis=1)
        y = cmat.sum(axis=0)
        cov_xx = numpy.sum(x * (n - x))
        cov_yy = numpy.sum(y * (n - y))
        if cov_xx == 0 or cov_yy == 0:
            return float('nan')
        i = cmat.diagonal()
        cov_xy = numpy.sum(i * n - x * y)
        return cov_xy / (cov_xx * cov_yy) ** 0.5

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype('int32', copy=False).ravel()
            pred = _as_numpy(pred)
            if pred.ndim > 1:
                pred = numpy.argmax(pred, axis=1)
            pred = pred.astype('int32', copy=False).ravel()
            n = int(max(pred.max(), label.max()))
            if n >= self.k:
                self._grow(n + 1 - self.k)
            bcm = numpy.zeros((self.k, self.k))
            for i, j in zip(pred, label):
                bcm[i, j] += 1
            self.lcm += bcm
            self.gcm += bcm
        self.num_inst += 1
        self.global_num_inst += 1

    @property
    def sum_metric(self):
        return self._calc_mcc(self.lcm) * self.num_inst

    @property
    def global_sum_metric(self):
        return self._calc_mcc(self.gcm) * self.global_num_inst

    @sum_metric.setter
    def sum_metric(self, _):
        pass

    @global_sum_metric.setter
    def global_sum_metric(self, _):
        pass

    def reset(self):
        self.global_num_inst = 0
        self.gcm = numpy.zeros((self.k, self.k))
        self.reset_local()

    def reset_local(self):
        self.num_inst = 0
        self.lcm = numpy.zeros((self.k, self.k))


@register
class Loss(EvalMetric):
    """Dummy metric averaging a loss output (parity: metric.py:1659)."""

    def __init__(self, name='loss', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, _, preds):
        if isinstance(preds, (list, tuple)) and not hasattr(preds, 'shape'):
            pred_list = list(preds)
        else:
            pred_list = [preds]
        for pred in pred_list:
            if hasattr(pred, 'data'):
                # device path: async sum now, one pull per get()
                self._device_accumulate(pred.data().sum(), pred.size)
                continue
            pred = _as_numpy(pred)
            loss = float(numpy.sum(pred))
            self.sum_metric += loss
            self.global_sum_metric += loss
            self.num_inst += pred.size
            self.global_num_inst += pred.size


@register
class Torch(Loss):
    """Legacy alias (parity: metric.py:1699)."""

    def __init__(self, name='torch', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)


@register
class Caffe(Loss):
    """Legacy alias (parity: metric.py:1708)."""

    def __init__(self, name='caffe', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)


@register
class CustomMetric(EvalMetric):
    """Metric from a ``feval(label, pred)`` function (metric.py:1717)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find('<') != -1:
                name = 'custom(%s)' % name
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.global_sum_metric += sum_metric
                self.num_inst += num_inst
                self.global_num_inst += num_inst
            else:
                self.sum_metric += reval
                self.global_sum_metric += reval
                self.num_inst += 1
                self.global_num_inst += 1

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy eval function as a metric (parity: metric.py:1810)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
