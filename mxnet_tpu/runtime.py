"""``mx.runtime`` — runtime feature detection (parity:
python/mxnet/runtime.py over ``src/libinfo.cc:169``).

The reference exposes compile-time flags (CUDA, CUDNN, MKLDNN, ...);
here features reflect what the JAX/XLA runtime actually provides on this
host, probed once at first query.
"""
from __future__ import annotations

import collections


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = bool(enabled)

    def __bool__(self):
        return self.enabled

    def __repr__(self):
        return "%s %s" % ("✔" if self.enabled else "✖", self.name)


class Features(collections.abc.Mapping):
    """Mapping of feature name → Feature (parity: runtime.Features)."""

    _instance = None

    def __init__(self):
        import jax

        platforms = set()
        try:
            platforms = {d.platform for d in jax.devices()}
        except Exception:
            pass
        try:
            import jax.experimental.pallas  # noqa: F401

            pallas = True
        except Exception:
            pallas = False
        self._features = {}
        for name, enabled in [
            ("TPU", bool(platforms & {"tpu", "axon"})),
            ("GPU", "gpu" in platforms or "cuda" in platforms),
            ("CPU", True),
            ("XLA", True),
            ("BF16", True),
            ("INT8", True),
            ("F64", True),
            ("PALLAS", pallas),
            ("DIST_KVSTORE", True),
            ("INT64_TENSOR_SIZE", True),
            ("SIGNAL_HANDLER", False),
            ("PROFILER", True),
            ("OPENCV", _has_module("cv2")),
            ("BLAS_OPEN", True),
        ]:
            self._features[name] = Feature(name, enabled)

    def __getitem__(self, key):
        return self._features[key.upper()]

    def __iter__(self):
        return iter(self._features)

    def __len__(self):
        return len(self._features)

    def is_enabled(self, name):
        return self._features[name.upper()].enabled

    def __repr__(self):
        return "[%s]" % ", ".join(repr(f) for f in self._features.values())


def _has_module(name):
    import importlib.util

    return importlib.util.find_spec(name) is not None


def feature_list():
    """Parity: runtime.feature_list()."""
    return list(Features().values())
