"""RecordIO: sequential and indexed record files.

Capability parity with the reference's ``python/mxnet/recordio.py``
(``MXRecordIO``, ``MXIndexedRecordIO``, ``IRHeader``/``pack``/``unpack``/
``pack_img``/``unpack_img``) and the dmlc-core on-disk format it wraps:
each record is ``[magic:u32][lrec:u32][data][pad to 4B]`` where the top 3
bits of ``lrec`` encode a continuation flag for records > 512MB (we write
only single-part records, but read multi-part ones).

Pure-Python host-side IO — record packing feeds the input pipeline which
runs on CPU regardless of backend, so there is no device-specific code here.
"""
from __future__ import annotations

import os
import struct
import numbers
from collections import namedtuple

import numpy as np

from .base import MXNetError

_MAGIC = 0xced7230a
_LREC_KIND_BITS = 29
_LREC_MASK = (1 << _LREC_KIND_BITS) - 1


def _encode_lrec(kind, length):
    return (kind << _LREC_KIND_BITS) | length


class MXRecordIO:
    """Sequential RecordIO reader/writer (parity: recordio.py:36)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.record = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.pid = os.getpid()
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        """Override pickling behavior (DataLoader workers fork/pickle us)."""
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d['is_open'] = is_open
        d.pop('record', None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        is_open = d['is_open']
        self.is_open = False
        self.record = None
        if is_open:
            self.open()

    def _check_pid(self, allow_reset=False):
        # after fork the file offset is shared with the parent: reopen
        if self.pid != os.getpid():
            if allow_reset:
                self.reset()
            else:
                raise MXNetError(
                    "RecordIO handle inherited across fork; call reset()")

    def close(self):
        if not self.is_open:
            return
        self.record.close()
        self.is_open = False
        self.pid = None

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        self._check_pid(allow_reset=False)
        data = bytes(buf)
        header = struct.pack('<II', _MAGIC, _encode_lrec(0, len(data)))
        self.record.write(header)
        self.record.write(data)
        pad = (4 - (len(data) % 4)) % 4
        if pad:
            self.record.write(b'\x00' * pad)

    def tell(self):
        assert self.writable
        return self.record.tell()

    def read(self):
        assert not self.writable
        self._check_pid(allow_reset=True)
        parts = []
        while True:
            header = self.record.read(8)
            if len(header) < 8:
                return b''.join(parts) if parts else None
            magic, lrec = struct.unpack('<II', header)
            if magic != _MAGIC:
                raise MXNetError("Invalid RecordIO magic in %s" % self.uri)
            kind = lrec >> _LREC_KIND_BITS
            length = lrec & _LREC_MASK
            data = self.record.read(length)
            pad = (4 - (length % 4)) % 4
            if pad:
                self.record.read(pad)
            parts.append(data)
            # kind: 0 = whole record, 1 = first part, 2 = middle, 3 = last
            if kind in (0, 3):
                return b''.join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO with a .idx sidecar (parity: recordio.py:161)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        self.fidx = open(self.idx_path, self.flag)
        if not self.writable:
            for line in iter(self.fidx.readline, ''):
                line = line.strip().split('\t')
                key = self.key_type(line[0])
                self.idx[key] = int(line[1])
                self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        super().close()
        if self.fidx is not None:
            self.fidx.close()
        self.fidx = None

    def __getstate__(self):
        d = super().__getstate__()
        d.pop('fidx', None)
        return d

    def seek(self, idx):
        assert not self.writable
        self._check_pid(allow_reset=True)
        pos = self.idx[idx]
        self.record.seek(pos)

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write('%s\t%d\n' % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


class RecordIOIterable:
    """Iterate all records of a RecordIO file (used by ImageRecordIter).

    Whole-file scans go through the native index + batch gather when the
    C++ helper is built (one mmap-style pass instead of per-record Python
    framing); otherwise the streaming Python reader.
    """

    def __init__(self, uri):
        self.uri = uri

    def __iter__(self):
        import mmap

        from . import native

        if native.available():
            f = mm = None
            try:
                f = open(self.uri, 'rb')
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                idx = native.index_buffer(mm)
            except (OSError, ValueError):
                idx = None
                if mm is not None:
                    mm.close()
                if f is not None:
                    f.close()
            if idx is not None:
                try:
                    offsets, lengths, flags = idx
                    if (flags == 0).all():
                        for o, n in zip(offsets.tolist(),
                                        lengths.tolist()):
                            yield bytes(mm[o:o + n])
                        return
                finally:
                    mm.close()
                    f.close()
        rec = MXRecordIO(self.uri, 'r')
        try:
            while True:
                item = rec.read()
                if item is None:
                    return
                yield item
        finally:
            rec.close()


# -- image record packing (parity: recordio.py IRHeader/pack/unpack) --------
IRHeader = namedtuple('HEADER', ['flag', 'label', 'id', 'id2'])
_IR_FORMAT = 'IfQQ'
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + raw bytes into one record payload."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, *header) + s
    return s


def unpack(s):
    """Unpack a record payload into (IRHeader, raw bytes)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, decoded image ndarray HWC).

    Decodes raw-ndarray payloads natively; JPEG/PNG payloads require an
    image codec which is not bundled (no OpenCV in image) — those raise.
    """
    header, s = unpack(s)
    img = _decode_image_bytes(s)
    return header, img


def _decode_image_bytes(s):
    # npy payload (our pack_img writes this) — portable, codec-free
    if s[:6] == b'\x93NUMPY':
        import io as _io
        return np.load(_io.BytesIO(s), allow_pickle=False)
    try:
        from PIL import Image  # optional, if present in the image
        import io as _io
        return np.asarray(Image.open(_io.BytesIO(s)))
    except ImportError:
        raise MXNetError(
            "Compressed image payloads need an image codec (PIL); "
            "re-pack with pack_img(..., quality=0) for raw npy payloads")


def pack_img(header, img, quality=95, img_fmt='.npy'):
    """Pack a header + image array. Default payload is lossless .npy
    (codec-free); '.jpg'/'.png' used when PIL is available."""
    img = np.asarray(img)
    if img_fmt in ('.npy', None) or quality == 0:
        import io as _io
        buf = _io.BytesIO()
        np.save(buf, img, allow_pickle=False)
        return pack(header, buf.getvalue())
    try:
        from PIL import Image
        import io as _io
        buf = _io.BytesIO()
        Image.fromarray(img.astype(np.uint8)).save(
            buf, format='JPEG' if img_fmt == '.jpg' else 'PNG',
            quality=quality)
        return pack(header, buf.getvalue())
    except ImportError:
        import io as _io
        buf = _io.BytesIO()
        np.save(buf, img, allow_pickle=False)
        return pack(header, buf.getvalue())
