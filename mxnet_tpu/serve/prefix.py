"""Radix-tree prefix cache over the paged KV arena (ISSUE 19).

Cross-request KV reuse: a token-id radix tree maps prompt prefixes to
arena pages so a request whose prompt starts with an already-served
prefix *splices* the cached pages into its block table instead of
re-prefilling them.  The tree is page-granular — every node is exactly
one FULL page (``page_size`` token ids) — which is what makes sharing
safe without copy-on-write machinery: a full page is immutable (its
``page_size`` slots were written by the prefill that produced it and
never touched again), so a spliced request only ever *reads* shared
pages and writes its own fresh tail.  The "COW fork" of a partially
filled tail page is recompute-on-write: the tail's few tokens are
simply not cached, and each request recomputes them in its own pages
via chunked prefill.  PR 13's purity property (arena state is a pure
function of the token stream — slot-0-fixed int8 scales, never
requantized) is what makes a cached page byte-identical to the page a
cold prefill would have produced, so greedy output is token-for-token
identical cache-on vs cache-off.

Reference counting lives in the arena (``retain``/``free`` with owner
tags): the cache holds one reference per cached page under the
``"prefix-cache"`` tag, every spliced request holds its own reference,
and a page recycles only when the last reference goes.  Eviction is LRU
over refcount-1 leaves (pages only the cache still holds) and runs
under arena pressure — ``Scheduler._admit`` calls ``evict`` when
``alloc`` comes back empty-handed.

Loop-thread-only and lock-free by contract, like the arena it wraps
(CD11xx): every mutator runs on the serve loop thread.
"""
from __future__ import annotations

from ..base import MXNetError
from ..telemetry import flight as _flight
from ..telemetry import metrics as _metrics
from ..testing import rescheck as _rescheck

#: Arena owner tag for every reference the cache holds.
CACHE_OWNER = "prefix-cache"


class _Node:
    """One full page of cached prefix: ``tokens`` is the page's
    ``page_size`` token ids (the radix edge label), ``page`` the arena
    page holding their KV."""

    __slots__ = ("page", "tokens", "parent", "children", "last_used")

    def __init__(self, page, tokens, parent, tick):
        self.page = page
        self.tokens = tokens
        self.parent = parent
        self.children = {}
        self.last_used = tick


class PrefixCache:
    """Radix tree of full KV pages keyed by their token ids."""

    def __init__(self, arena):
        self.arena = arena
        self._root = _Node(None, (), None, 0)
        # deterministic LRU clock: a counter, not wall time, so seeded
        # chaos runs evict identically twice
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.cached_tokens = 0
        self.evictions = 0
        self.inserts = 0
        self.pages = 0            # live nodes (== cached pages)
        self._res = None          # rescheck token while non-empty

    # -- lookup -----------------------------------------------------------
    def match(self, tokens):
        """Longest cached prefix of ``tokens``, page-aligned.

        Returns ``(pages, hit_tokens)``.  The hit is capped so at least
        one prompt token remains to prefill — the last prompt position's
        logits seed the first generated token, so a 100% hit must still
        recompute its final page's worth of tokens.
        """
        ps = self.arena.geometry.page_size
        self._tick += 1
        node, pages = self._root, []
        for d in range(len(tokens) // ps):
            child = node.children.get(tuple(tokens[d * ps:(d + 1) * ps]))
            if child is None:
                break
            child.last_used = self._tick
            pages.append(child.page)
            node = child
        hit = len(pages) * ps
        while pages and hit >= len(tokens):
            pages.pop()
            hit -= ps
        return list(pages), hit

    def record_hit(self, hit_tokens, n_pages):
        """Count a splice that actually happened (the scheduler calls
        this at admission, after the arena paged the request — a match
        stalled on arena pressure is re-tried, not double-counted)."""
        self.hits += 1
        self.cached_tokens += hit_tokens
        if _metrics.enabled():
            _metrics.counter(
                "mxnet_serve_prefix_hits_total",
                help="prefill requests that spliced at least one cached "
                     "prefix page").inc()
            _metrics.counter(
                "mxnet_serve_prefix_cached_tokens_total",
                help="prompt tokens served from the prefix cache "
                     "instead of being re-prefilled").inc(hit_tokens)
        _flight.record("prefix.hit", tokens=hit_tokens, pages=n_pages)

    def record_miss(self):
        self.misses += 1
        if _metrics.enabled():
            _metrics.counter(
                "mxnet_serve_prefix_misses_total",
                help="prefill requests that found no cached prefix "
                     "page").inc()

    # -- population -------------------------------------------------------
    def insert(self, tokens, pages):
        """Cache the full pages of a just-prefilled prompt.

        ``pages`` is the owning request's page list; for each full page
        of ``tokens`` not already in the tree the cache takes its own
        reference (``retain``) on the request's page — the request's
        later ``free`` then decrements instead of recycling.  Depths
        already cached keep the existing page (first writer wins; the
        duplicate page stays private to its request).
        """
        ps = self.arena.geometry.page_size
        self._tick += 1
        node, added = self._root, 0
        for d in range(len(tokens) // ps):
            key = tuple(tokens[d * ps:(d + 1) * ps])
            child = node.children.get(key)
            if child is None:
                page = pages[d]
                self.arena.retain([page], CACHE_OWNER)
                child = _Node(page, key, node, self._tick)
                node.children[key] = child
                self.pages += 1
                self.inserts += 1
                added += 1
            else:
                child.last_used = self._tick
            node = child
        if added:
            if self._res is None and _rescheck.enabled():
                self._res = _rescheck.acquire(
                    "prefix", CACHE_OWNER, scope=self.arena.res_scope)
            _flight.record("prefix.insert", pages=added)
        return added

    # -- pressure ---------------------------------------------------------
    def evict(self, n_needed):
        """Free up to ``n_needed`` pages, LRU over evictable leaves.

        A node is evictable when it has no children (evicting an inner
        node would orphan its suffix) and the arena refcount of its page
        is 1 — only the cache holds it; pages a live request or session
        still references are never evicted.  Evicting a leaf can expose
        its parent as the next candidate, so the scan repeats until the
        target is met or nothing is evictable.
        """
        freed = 0
        while freed < n_needed:
            victim = None
            stack = list(self._root.children.values())
            while stack:
                n = stack.pop()
                if n.children:
                    stack.extend(n.children.values())
                elif self.arena.refcount(n.page) == 1:
                    if victim is None or n.last_used < victim.last_used:
                        victim = n
            if victim is None:
                break
            del victim.parent.children[victim.tokens]
            self.arena.free([victim.page], owner=CACHE_OWNER)
            self.pages -= 1
            self.evictions += 1
            freed += 1
        if freed:
            if _metrics.enabled():
                _metrics.counter(
                    "mxnet_serve_prefix_evictions_total",
                    help="cached prefix pages evicted (LRU) under arena "
                         "pressure").inc(freed)
            _flight.record("prefix.evict", pages=freed)
        if self.pages == 0 and self._res is not None:
            _rescheck.release(self._res)
            self._res = None
        return freed

    # -- teardown ---------------------------------------------------------
    def release_all(self):
        """Drop every cache reference (drain / stop / swap / fail_all).

        Shared pages simply decrement — a live request or session still
        holding them keeps them allocated; cache-only pages recycle.
        """
        dropped = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self.arena.free([n.page], owner=CACHE_OWNER)
            dropped += 1
        self._root = _Node(None, (), None, self._tick)
        self.pages = 0
        if self._res is not None:
            _rescheck.release(self._res)
            self._res = None
        if dropped:
            _flight.record("prefix.release", pages=dropped)
        return dropped

    # -- introspection ----------------------------------------------------
    def hit_rate(self):
        seen = self.hits + self.misses
        return self.hits / seen if seen else 0.0

    def stats(self):
        return {
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_hit_rate": round(self.hit_rate(), 4),
            "prefix_cached_tokens": self.cached_tokens,
            "prefix_pages": self.pages,
            "prefix_evictions": self.evictions,
        }

    def assert_quiescent(self):
        """The cache holds no pages (used after ``release_all`` in
        drain/stop paths before the arena's own quiescence check)."""
        if self.pages or self._root.children:
            raise MXNetError("prefix cache not quiescent: %d page(s) "
                             "still cached" % self.pages)
