"""CLI: ``python -m mxnet_tpu.serve --bundle llama.mxaot --port 8000``.

Loads the AOT serving bundle (zero live compiles), starts the
continuous-batching loop, and exposes the stdlib HTTP front:
``POST /v1/generate {"prompt": [...ids], "max_new_tokens": n}``,
``GET /metrics`` (Prometheus), ``GET /healthz`` (scheduler stats).

SIGTERM (what an orchestrator sends on pod eviction / rollout) triggers
the graceful path: stop admission (503 + Retry-After), finish in-flight
work within ``--drain-timeout`` (default ``MXNET_SERVE_DRAIN_TIMEOUT``),
fail stragglers typed, then exit.  Ctrl-C takes the same path.
"""
from __future__ import annotations

import argparse
import signal
import threading

from .server import LlamaServer


def main(argv=None):
    ap = argparse.ArgumentParser(prog="mxnet_tpu.serve")
    ap.add_argument("--bundle", required=True,
                    help="MXAOT1 serving bundle (export_serving_bundle)")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--queue-depth", type=int, default=None)
    ap.add_argument("--spec-k", type=int, default=None,
                    help="runtime speculative draft count (default: the "
                         "bundle's compiled spec_k; 0 disables)")
    ap.add_argument("--kv-dtype", default=None,
                    help="assert the bundle's KV arena dtype (e.g. int8) "
                         "— refuses to serve on mismatch")
    ap.add_argument("--drain-timeout", type=float, default=None,
                    help="seconds to let in-flight work finish on "
                         "SIGTERM/Ctrl-C (default: "
                         "MXNET_SERVE_DRAIN_TIMEOUT or 30)")
    args = ap.parse_args(argv)
    srv = LlamaServer(args.bundle, queue_depth=args.queue_depth,
                      spec_k=args.spec_k, kv_dtype=args.kv_dtype).start()
    host, port = srv.serve_http(port=args.port, host=args.host)
    term = threading.Event()
    # registered before the banner: the orchestrator (or a test) may
    # SIGTERM the moment it sees the port
    signal.signal(signal.SIGTERM, lambda *a: term.set())
    print("serving %s on http://%s:%d  [%s]"
          % (args.bundle, host, port, srv.geometry.describe()))
    try:
        term.wait()
    except KeyboardInterrupt:
        pass
    stragglers = srv.drain(timeout=args.drain_timeout)
    srv.stop()
    if stragglers:
        print("drain timed out: %d request(s) failed typed" % stragglers)


if __name__ == "__main__":
    main()
