"""CLI: ``python -m mxnet_tpu.serve --bundle llama.mxaot --port 8000``.

Loads the AOT serving bundle (zero live compiles), starts the
continuous-batching loop, and exposes the stdlib HTTP front:
``POST /v1/generate {"prompt": [...ids], "max_new_tokens": n}``,
``POST /v1/chat`` (multi-turn, pinned sessions), ``GET /metrics``
(Prometheus), ``GET /healthz`` (scheduler stats).

``--fleet N`` (N > 1) starts N in-process replicas behind a
:class:`FleetRouter` front instead — queue-depth-aware routing, bounded
retries, circuit-breaker ejection — on the same HTTP surface
(``tools/mxfleet.py`` is the richer fleet CLI: remote replicas, status,
rolling deploys).

SIGTERM (what an orchestrator sends on pod eviction / rollout) triggers
the graceful path: stop admission (503 + Retry-After), finish in-flight
work within ``--drain-timeout`` (default ``MXNET_SERVE_DRAIN_TIMEOUT``),
fail stragglers typed, then exit.  Ctrl-C takes the same path.
"""
from __future__ import annotations

import argparse
import os
import signal
import threading

from .fleet import FleetRouter
from .server import LlamaServer


def main(argv=None):
    ap = argparse.ArgumentParser(prog="mxnet_tpu.serve")
    ap.add_argument("--bundle", required=True,
                    help="MXAOT1 serving bundle (export_serving_bundle)")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--fleet", type=int, default=1, metavar="N",
                    help="serve N in-process replicas behind a "
                         "FleetRouter front (default 1: plain server)")
    ap.add_argument("--queue-depth", type=int, default=None)
    ap.add_argument("--spec-k", type=int, default=None,
                    help="runtime speculative draft count (default: the "
                         "bundle's compiled spec_k; 0 disables)")
    ap.add_argument("--kv-dtype", default=None,
                    help="assert the bundle's KV arena dtype (e.g. int8) "
                         "— refuses to serve on mismatch")
    ap.add_argument("--drain-timeout", type=float, default=None,
                    help="seconds to let in-flight work finish on "
                         "SIGTERM/Ctrl-C (default: "
                         "MXNET_SERVE_DRAIN_TIMEOUT or 30)")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable cross-request KV prefix sharing "
                         "(sets MXNET_SERVE_PREFIX_CACHE=0)")
    ap.add_argument("--session-ttl", type=float, default=None,
                    help="idle seconds before a pinned /v1/chat session "
                         "is evicted (default: MXNET_SERVE_SESSION_TTL "
                         "or 600)")
    args = ap.parse_args(argv)
    if args.no_prefix_cache:
        os.environ["MXNET_SERVE_PREFIX_CACHE"] = "0"
    if args.session_ttl is not None:
        os.environ["MXNET_SERVE_SESSION_TTL"] = str(args.session_ttl)
    if args.fleet < 1:
        ap.error("--fleet must be >= 1")

    def _make_server():
        return LlamaServer(args.bundle, queue_depth=args.queue_depth,
                           spec_k=args.spec_k,
                           kv_dtype=args.kv_dtype).start()

    term = threading.Event()
    if args.fleet == 1:
        srv = _make_server()
        host, port = srv.serve_http(port=args.port, host=args.host)
        # registered before the banner: the orchestrator (or a test) may
        # SIGTERM the moment it sees the port
        signal.signal(signal.SIGTERM, lambda *a: term.set())
        print("serving %s on http://%s:%d  [%s]"
              % (args.bundle, host, port, srv.geometry.describe()))
        try:
            term.wait()
        except KeyboardInterrupt:
            pass
        stragglers = srv.drain(timeout=args.drain_timeout)
        srv.stop()
        if stragglers:
            print("drain timed out: %d request(s) failed typed"
                  % stragglers)
        return

    servers = [_make_server() for _ in range(args.fleet)]
    router = FleetRouter(servers).start()
    host, port = router.serve_http(port=args.port, host=args.host)
    signal.signal(signal.SIGTERM, lambda *a: term.set())
    print("serving fleet n=%d %s on http://%s:%d  [%s]"
          % (args.fleet, args.bundle, host, port,
             servers[0].geometry.describe()))
    try:
        term.wait()
    except KeyboardInterrupt:
        pass
    stragglers = 0
    for srv in servers:  # drain one at a time: the router steers away
        stragglers += srv.drain(timeout=args.drain_timeout)
    router.stop()
    for srv in servers:
        srv.stop()
    if stragglers:
        print("drain timed out: %d request(s) failed typed" % stragglers)


if __name__ == "__main__":
    main()
