"""N-gram self-speculative draft proposer (ISSUE 13 tentpole).

Draft-free speculation: instead of a separate draft model, each lane
proposes its own continuation by matching the last n-gram of its emitted
stream (prompt + generated tokens) against earlier occurrences in that
same stream and replaying what followed the most recent match.  Greedy
decode on repetitive text — code, boilerplate, small models collapsing
into cycles — accepts most of these drafts; on non-repetitive text the
verify pass rejects them and the lane degrades to ordinary one-token
decode, never worse than correct (acceptance is exact, see
scheduler._verify_once).

Pure numpy, no jax: proposing runs on the host between compiled verify
calls and must never trigger a live jit.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

# longest n-gram tried first; 3 balances match specificity against the
# chance of finding any match at all in short histories
DEFAULT_MAX_NGRAM = 3


def propose_ngram(history, k, max_ngram=DEFAULT_MAX_NGRAM,
                  with_match=False):
    """Propose ``k`` draft tokens continuing ``history``.

    Finds the most recent earlier occurrence of the longest suffix
    n-gram (``n`` from ``max_ngram`` down to 1) of ``history`` and
    returns the ``k`` tokens that followed it, padded by repeating the
    final draft when the match sits near the end.  Falls back to
    repeating the last token when nothing matches — a cheap guess that
    is free when rejected.

    Returns a list of ``k`` ints; ``history`` must be non-empty.  With
    ``with_match=True`` returns ``(drafts, n_matched)`` where
    ``n_matched`` is the length of the suffix n-gram that matched (0 on
    the repeat-last fallback) — the scheduler's hybrid policy only pays
    for a verify block when some lane has a real match.
    """
    if k <= 0:
        raise MXNetError("propose_ngram needs k > 0, got %d" % k)
    h = np.asarray(history, dtype=np.int64)
    n_hist = h.shape[0]
    if n_hist == 0:
        raise MXNetError("propose_ngram needs a non-empty history")
    for n in range(min(int(max_ngram), n_hist - 1), 0, -1):
        tail = h[n_hist - n:]
        # windows over history[:-1] so a match always has a continuation
        wins = np.lib.stride_tricks.sliding_window_view(h[:-1], n)
        hits = np.flatnonzero((wins == tail).all(axis=1))
        if hits.size == 0:
            continue
        start = int(hits[-1]) + n          # most recent match continues here
        cont = h[start:start + k]
        if cont.shape[0] < k:              # match near the end: pad
            pad = cont[-1] if cont.shape[0] else h[-1]
            cont = np.concatenate(
                [cont, np.full(k - cont.shape[0], pad, dtype=np.int64)])
        drafts = [int(t) for t in cont]
        return (drafts, n) if with_match else drafts
    drafts = [int(h[-1])] * k
    return (drafts, 0) if with_match else drafts


class NgramProposer:
    """Incremental index over one lane's stream: same match rule as
    :func:`propose_ngram`, O(max_ngram) per propose instead of a full
    history scan.

    The scheduler proposes for every active lane on every decode step,
    so the scan version's cost (~0.2 ms per lane per step) eats a
    double-digit share of a CPU decode budget.  This class keeps a dict
    per n-gram length mapping each n-gram to its most recent occurrence
    strictly inside ``history[:-1]`` (so a match always has a
    continuation), updated as tokens are appended — the index is only
    ever appended to, mirroring the lane's emitted stream exactly.
    """

    __slots__ = ("history", "max_ngram", "_index")

    def __init__(self, history, max_ngram=DEFAULT_MAX_NGRAM):
        self.max_ngram = int(max_ngram)
        self.history = []
        self._index = [None] + [dict() for _ in range(self.max_ngram)]
        for tok in history:
            self.append(tok)

    def append(self, tok):
        h = self.history
        h.append(int(tok))
        # the windows that just became searchable end at len-2: windows
        # are only indexed once a continuation token exists after them
        for n in range(1, self.max_ngram + 1):
            s = len(h) - 1 - n
            if s >= 0:
                self._index[n][tuple(h[s:s + n])] = s

    def extend(self, toks):
        for tok in toks:
            self.append(tok)

    def propose(self, k):
        """``(drafts, n_matched)`` — identical to ``propose_ngram(
        history, k, max_ngram, with_match=True)``."""
        if k <= 0:
            raise MXNetError("propose needs k > 0, got %d" % k)
        h = self.history
        if not h:
            raise MXNetError("propose needs a non-empty history")
        for n in range(min(self.max_ngram, len(h) - 1), 0, -1):
            s = self._index[n].get(tuple(h[len(h) - n:]))
            if s is None:
                continue
            cont = h[s + n:s + n + k]
            if len(cont) < k:
                pad = cont[-1] if cont else h[-1]
                cont = cont + [pad] * (k - len(cont))
            return list(cont), n
        return [h[-1]] * k, 0
