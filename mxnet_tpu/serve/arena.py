"""Paged KV-cache arena: fixed-size pages, block tables, liveness-safe
reuse.

The whole cache is two NDArrays shaped ``(L, P, page, KV, D)`` (one for
K, one for V).  Sequences own pages through host-side block tables —
int32 rows mapping ``token_position // page_size`` to a page index — so
admission never copies or reshapes cache memory: allocating a sequence
is popping page ids off a free list, finishing one is pushing them back.
Page 0 is reserved as the **null page**: inactive decode slots point
their block-table row at it and scribble there harmlessly.

The arena is **loop-thread-only and lock-free by contract** (CD11xx):
every mutator — allocate, append, finish, defrag — runs on the serve
loop thread (or the caller's thread before ``start()``), never
concurrently.  Cross-thread visibility goes through the scheduler,
whose lock (``serve.sched`` under ``MXNET_LOCKCHECK=1``) is dropped
before any arena call.  Do not add locks here; add state to the
scheduler if another thread ever needs it.

Reuse safety rides on the engine's var-dependency tracking.  The decode
/prefill executables *donate* the KV buffers on accelerator backends
(XLA deletes them; see model._donate_kv for the CPU exception), and a
freed page may be handed to a new sequence while imperative NDArray ops
— a debug checksum, an eviction scorer — sit deferred in an open bulk
segment that captured the old buffer as an ext input.  Before any
donating call or page reuse the arena asks ``Engine.pending_reads`` and
drains via ``flush_if_referencing``, so a pending segment always reads
the pre-reuse snapshot (tests/test_serve.py stress-tests this).
"""
from __future__ import annotations

import collections

import numpy as np

from ..base import MXNetError
from ..engine import Engine
from ..telemetry import memdump as _memdump
from ..telemetry import metrics as _metrics
from ..testing import rescheck as _rescheck


class PagedKVArena:
    """Block-table allocator over two arena NDArrays (K and V)."""

    def __init__(self, geometry, mesh=None, kv_spec=None):
        import jax

        from ..ndarray.ndarray import NDArray

        self.geometry = geometry
        shape = geometry.kv_shape()
        # int8 geometries store quantized pages plus one float32 scale
        # per (layer, page) for each of K and V — the scales live on
        # device too, as executable state alongside the kv buffers
        self.quantized = geometry.quantized
        dtype = np.dtype(geometry.kv_dtype)
        # device_put, NOT nd.zeros: a serving process must not push ops
        # (zero live compiles — the tentpole claim of the AOT warm start)
        # With mesh=/kv_spec= the arena buffers live sharded on the mesh
        # — KV heads (dim 3) on the tp axis is the canonical spec; the
        # serving executables' kv arguments then inherit the placement.
        placement = None
        if mesh is not None or kv_spec is not None:
            from .. import sharding as _sharding

            placement = _sharding.named_sharding(mesh, kv_spec)
            _sharding.maybe_verify(placement.mesh, placement.spec,
                                   shape=shape, what="kv_arena")
        self.kv_k = NDArray(jax.device_put(np.zeros(shape, dtype),
                                           placement))
        self.kv_v = NDArray(jax.device_put(np.zeros(shape, dtype),
                                           placement))
        _memdump.tag(self.kv_k.data(), origin="kv_page", label="arena.k")
        _memdump.tag(self.kv_v.data(), origin="kv_page", label="arena.v")
        self.k_scale = self.v_scale = None
        if self.quantized:
            # scales are tiny and replicated — never sharded
            sshape = geometry.scale_shape()
            self.k_scale = NDArray(jax.device_put(
                np.zeros(sshape, np.float32)))
            self.v_scale = NDArray(jax.device_put(
                np.zeros(sshape, np.float32)))
            _memdump.tag(self.k_scale.data(), origin="kv_page",
                         label="arena.k_scale")
            _memdump.tag(self.v_scale.data(), origin="kv_page",
                         label="arena.v_scale")
        # page 0 is the null page — never allocated
        self._free = collections.deque(range(1, geometry.num_pages))
        # page id -> LIST of owner tags.  One entry per reference: the
        # allocating request, plus (ISSUE 19) the prefix cache and any
        # session or spliced request sharing the page.  refcount ==
        # len(list); the page recycles only when the list empties, so
        # ``free`` under sharing decrements instead of recycling.
        self._owner = {}
        # MXNET_RESCHECK: one token per live allocation, keyed by its
        # first page (plain dict — loop-thread-only like _owner)
        self.res_scope = "arena:%x" % id(self)
        self._res = {}
        self.liveness_flushes = 0  # times a pending segment forced a flush

    # -- capacity ---------------------------------------------------------
    @property
    def free_pages(self):
        return len(self._free)

    @property
    def total_pages(self):
        """Allocatable pages (the null page is not part of the budget)."""
        return self.geometry.num_pages - 1

    def pages_needed(self, total_tokens):
        """Pages a sequence of ``total_tokens`` (prompt + budget) needs."""
        return -(-int(total_tokens) // self.geometry.page_size)

    def utilization(self):
        used = self.total_pages - len(self._free)
        return used / float(self.total_pages)

    # -- alloc/free -------------------------------------------------------
    def alloc(self, n_pages, owner):
        """Claim ``n_pages`` for ``owner``; None when the arena is full.

        Handing a previously-freed page to a new owner is the reuse
        moment: drain any bulk segment still reading the arena buffers
        first, so deferred imperative work observes the pre-reuse
        snapshot before the next executable call overwrites the page.
        """
        n_pages = int(n_pages)
        if n_pages <= 0:
            raise MXNetError("alloc wants a positive page count")
        if n_pages > self.geometry.max_pages_per_seq:
            raise MXNetError(
                "sequence needs %d pages but max_pages_per_seq is %d "
                "(max context %d tokens)"
                % (n_pages, self.geometry.max_pages_per_seq,
                   self.geometry.max_context))
        if n_pages > len(self._free):
            return None
        self.drain_pending_readers("serve_arena_alloc")
        pages = [self._free.popleft() for _ in range(n_pages)]
        for p in pages:
            self._owner[p] = [owner]
        if _rescheck.enabled():
            self._res[pages[0]] = _rescheck.acquire(
                "arena", owner, scope=self.res_scope)
        self._gauges()
        return pages

    def retain(self, pages, owner):
        """Add one reference per page for ``owner`` (prefix-cache splice,
        session pin).  The pages must already be allocated — retaining a
        free or null page is block-table corruption, not a cache miss."""
        for p in pages:
            owners = self._owner.get(p)
            if owners is None or p == 0:
                raise MXNetError("retaining page %d that is not allocated"
                                 % p)
            owners.append(owner)
        self._gauges()

    def free(self, pages, owner=None):
        """Drop one reference per page; recycle pages whose count hits 0.

        Double frees stay guarded under sharing: the ``owner`` tag must
        hold a reference on every page it frees, and a page recycles
        exactly once — when its last reference goes (refcounted free
        must not confuse the RL12xx page tracking, so the rescheck token
        for an allocation group releases only when its first page truly
        returns to the free list).
        """
        for p in pages:
            owners = self._owner.get(p)
            if owners is None or p == 0:
                raise MXNetError("freeing page %d that is not allocated"
                                 % p)
            if owner is not None:
                if owner not in owners:
                    raise MXNetError(
                        "page %d is owned by %r, not %r — double free or "
                        "block-table corruption" % (p, owners, owner))
                owners.remove(owner)
            else:
                owners.pop()
            if not owners:
                del self._owner[p]
                self._free.append(p)
                _rescheck.release(self._res.pop(p, None))
        self._gauges()

    def owner_of(self, page):
        owners = self._owner.get(page)
        return owners[0] if owners else None

    def refcount(self, page):
        """Live references on ``page`` (0 when free / null)."""
        return len(self._owner.get(page, ()))

    def shared_pages(self):
        """Pages currently referenced by more than one owner."""
        return sum(1 for o in self._owner.values() if len(o) > 1)

    def assert_quiescent(self):
        """Leak check: every allocatable page is back on the free list
        and nothing but the null page is live.  Raises ``MXNetError``
        naming the leaked pages and their owners — the serve
        chaos/expiry/cancel/drain tests call this after every scenario
        (ISSUE 15: a robustness path that loses pages is a slow death).
        """
        problems = []
        if self._owner:
            by_owner = {}
            for p, owners in sorted(self._owner.items()):
                for o in owners:
                    by_owner.setdefault(o, []).append(p)
            problems.append("%d live page(s): %s" % (
                len(self._owner),
                ", ".join("owner %r holds %s" % (o, pages)
                          for o, pages in sorted(by_owner.items(),
                                                 key=lambda kv: str(kv[0])))))
        free = list(self._free)
        expect = set(range(1, self.geometry.num_pages))
        if len(free) != len(set(free)):
            problems.append("free list has duplicates")
        if set(free) - expect:
            problems.append("free list holds invalid pages %s"
                            % sorted(set(free) - expect))
        missing = expect - set(free) - set(self._owner)
        if missing:
            problems.append("page(s) %s neither free nor owned (leaked)"
                            % sorted(missing))
        if problems:
            raise MXNetError("arena not quiescent: "
                             + "; ".join(problems))

    def reset(self):
        """Hard reset after loop-crash containment: rebuild the free
        list and re-zero the buffers with plain ``device_put`` (no ops —
        zero live compiles holds even through a crash).  Only legal once
        every request was failed (``Scheduler.fail_all``): resetting
        under a live sequence would be silent KV corruption."""
        import jax

        if self._owner:
            raise MXNetError(
                "arena reset with %d live page(s) — fail the in-flight "
                "requests first" % len(self._owner))
        self._free = collections.deque(range(1, self.geometry.num_pages))
        for tok in self._res.values():
            _rescheck.release(tok)
        self._res.clear()
        dtype = np.dtype(self.geometry.kv_dtype)
        zeros = np.zeros(self.geometry.kv_shape(), dtype)
        self.kv_k._set_data(jax.device_put(zeros))
        self.kv_v._set_data(jax.device_put(zeros))
        _memdump.tag(self.kv_k.data(), origin="kv_page", label="arena.k")
        _memdump.tag(self.kv_v.data(), origin="kv_page", label="arena.v")
        if self.quantized:
            szeros = np.zeros(self.geometry.scale_shape(), np.float32)
            self.k_scale._set_data(jax.device_put(szeros))
            self.v_scale._set_data(jax.device_put(szeros))
            _memdump.tag(self.k_scale.data(), origin="kv_page",
                         label="arena.k_scale")
            _memdump.tag(self.v_scale.data(), origin="kv_page",
                         label="arena.v_scale")
        self._gauges()

    def block_row(self, pages):
        """Block-table row (maxp,) int32 for a page list; unused entries
        point at the null page."""
        row = np.zeros(self.geometry.max_pages_per_seq, dtype=np.int32)
        row[: len(pages)] = pages
        return row

    # -- engine liveness --------------------------------------------------
    def buffers(self):
        """The concrete arena state buffers in executable argument order
        (for liveness queries/donation): ``(k, v)``, or ``(k, v,
        k_scale, v_scale)`` when the arena is quantized."""
        if self.quantized:
            return (self.kv_k.data(), self.kv_v.data(),
                    self.k_scale.data(), self.v_scale.data())
        return (self.kv_k.data(), self.kv_v.data())

    def drain_pending_readers(self, origin):
        """Flush this thread's bulk segment if it still reads the arena.

        Called before page reuse and before every donating executable
        call: XLA deletes donated buffers even while a pending segment
        holds them as ext inputs, and a recycled page must not be
        overwritten under a deferred read.  Cheap no-op when nothing
        pends (the steady-state serving case — no imperative ops at all).
        """
        eng = Engine.get()
        bufs = self.buffers()
        if eng.pending_reads(bufs):
            eng.flush_if_referencing(bufs, origin)
            self.liveness_flushes += 1
            if _metrics.enabled():
                _metrics.counter(
                    "mxnet_serve_arena_liveness_flushes_total",
                    help="bulk-segment flushes forced because a pending "
                         "segment still read the KV arena").inc()

    def adopt(self, new_k, new_v, new_k_scale=None, new_v_scale=None):
        """Swap in the post-call arena buffers (when donation is on the
        executables delete the old ones, so this is the only live
        reference handoff; without donation the old buffers simply drop
        their last reference here).  Quantized arenas must hand the two
        scale arrays back too — they are executable state."""
        self.kv_k._set_data(new_k)
        self.kv_v._set_data(new_v)
        # re-attribute: the swap is the only place fresh arena storage
        # appears, and an untagged buffer would sweep as "temp"
        _memdump.tag(new_k, origin="kv_page", label="arena.k")
        _memdump.tag(new_v, origin="kv_page", label="arena.v")
        if self.quantized:
            if new_k_scale is None or new_v_scale is None:
                raise MXNetError("quantized arena adopt needs the scale "
                                 "arrays back from the executable")
            self.k_scale._set_data(new_k_scale)
            self.v_scale._set_data(new_v_scale)
            _memdump.tag(new_k_scale, origin="kv_page",
                         label="arena.k_scale")
            _memdump.tag(new_v_scale, origin="kv_page",
                         label="arena.v_scale")

    def _gauges(self):
        if _metrics.enabled():
            _metrics.gauge(
                "mxnet_serve_arena_utilization",
                help="fraction of allocatable KV pages in use",
            ).set(self.utilization())
            _metrics.gauge(
                "mxnet_serve_arena_pages_in_use",
                help="allocated KV pages (null page excluded)",
            ).set(self.total_pages - len(self._free))
            _metrics.gauge(
                "mxnet_serve_prefix_shared_pages",
                help="arena pages held by more than one reference "
                     "(prefix-cache hits, pinned sessions)",
            ).set(self.shared_pages())
