"""LlamaServer: AOT warm-start serving over the paged arena.

Startup deserializes the bundle's decode + prefill executables (PR 7
``MXAOT1`` path), builds the arena with plain ``device_put`` zeros, and
spins one scheduler thread — **no jit anywhere on the serving path**, so
``mxnet_compiles_total`` stays empty for the process lifetime (the
serve-smoke CI job asserts exactly this from the telemetry dump).

The runner is the only jax-touching layer: it drains pending bulk
segments that still read the arena (the executables donate the KV
buffers on accelerator backends — see model._donate_kv), calls the
deserialized executable, adopts the new buffers into
the arena, and hands numpy logits back to the jax-free scheduler.
Sampling is host-side numpy, so the decode loop's device work is exactly
one executable call per step.

``static_generate`` is the naive baseline the serving bench compares
against: fixed batches, no mid-flight admission, every batch runs until
its slowest member finishes — same runner, same arena, so the measured
gap is pure scheduling.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import math
import os
import select
import socket
import threading
import time

import numpy as np

from ..base import MXNetError
from ..telemetry import flight as _flight
from ..telemetry import memdump as _memdump
from ..telemetry import metrics as _metrics
from ..testing import faults as _faults
from ..testing import lockcheck as _lockcheck
from ..testing import rescheck as _rescheck
from .arena import PagedKVArena
from .scheduler import (Request, Scheduler, ServeCancelled,
                        ServeDeadlineExceeded, ServeDraining,
                        ServeInternalError, ServeQueueFull,
                        ServeSessionBusy, ServeSessionUnknown, ServeShutdown,
                        _env_float, _env_int)

_SERVER_IDS = itertools.count()


def _bundle_sha(path):
    """Short content hash of the loaded bundle — the /healthz field a
    fleet router uses to detect version drift and assert convergence
    after a rolling deploy.  Hashes file bytes when ``path`` is a real
    bundle; falls back to hashing the string for the scripted swaps the
    chaos suite performs (``from_parts`` servers have no file)."""
    if path is None:
        return None
    try:
        with open(path, "rb") as f:
            digest = hashlib.sha256(f.read())
    except OSError:
        digest = hashlib.sha256(str(path).encode())
    return digest.hexdigest()[:16]


def _retry_after_header(retry_after_s):
    """HTTP Retry-After is delta-seconds as a non-negative integer; the
    scheduler's hint is a clamped float — round up, floor at 1."""
    try:
        return str(max(1, int(math.ceil(float(retry_after_s)))))
    except (TypeError, ValueError):
        return "1"


class AOTRunner:
    """Executes the bundle's compiled graphs against one arena."""

    def __init__(self, executables, arena):
        self._exes = executables
        self.arena = arena
        g = arena.geometry
        self._pad = {b: np.zeros(b, dtype=np.int32)
                     for b in g.prefill_buckets}

    def _call(self, exe, origin, *args):
        """Drain pending readers, run ``exe`` over the arena state (kv
        buffers, plus scale arrays for int8), adopt the returned state,
        hand back the trailing logits output."""
        self.arena.drain_pending_readers(origin)
        outs = exe(*self.arena.buffers(), *args)
        self.arena.adopt(*outs[:-1])
        return outs[-1]

    def prefill(self, bucket, tokens, length, block_row):
        exe = self._exes.get("prefill_%d" % bucket)
        if exe is None:
            raise MXNetError("bundle has no prefill executable for "
                             "bucket %d" % bucket)
        padded = self._pad[bucket].copy()
        padded[:length] = tokens
        logits = self._call(exe, "serve_prefill", padded, np.int32(length),
                            block_row.astype(np.int32))
        _memdump.tag(logits, origin="activation", label="prefill_logits")
        return np.asarray(logits)  # mxlint: allow-host-sync

    def decode(self, tokens, positions, block_tables):
        logits = self._call(self._exes["decode"], "serve_decode",
                            tokens.astype(np.int32),
                            positions.astype(np.int32),
                            block_tables.astype(np.int32))
        _memdump.tag(logits, origin="activation", label="decode_logits")
        return np.asarray(logits)  # mxlint: allow-host-sync

    def verify(self, tokens, positions, block_tables):
        """Speculative verify: tokens (B, spec_k+1) -> logits
        (B, spec_k+1, V), from the bundle's compiled ``verify``
        executable — still zero live jits."""
        exe = self._exes.get("verify")
        if exe is None:
            raise MXNetError(
                "bundle has no verify executable — re-export with "
                "spec_k > 0 to enable speculative decoding")
        logits = self._call(exe, "serve_verify",
                            tokens.astype(np.int32),
                            positions.astype(np.int32),
                            block_tables.astype(np.int32))
        _memdump.tag(logits, origin="activation", label="verify_logits")
        return np.asarray(logits)  # mxlint: allow-host-sync

    def chunk(self, tokens, positions, block_tables):
        """Chunked prefill: tokens (B, prefill_chunk) -> logits
        (B, prefill_chunk, V) from the bundle's ``chunk`` executable —
        the same multi-token shape as verify, compiled at the chunk
        width instead of spec_k+1."""
        exe = self._exes.get("chunk")
        if exe is None:
            raise MXNetError(
                "bundle has no chunk executable — re-export with "
                "prefill_chunk > 0 to enable chunked prefill")
        logits = self._call(exe, "serve_chunk",
                            tokens.astype(np.int32),
                            positions.astype(np.int32),
                            block_tables.astype(np.int32))
        _memdump.tag(logits, origin="activation", label="chunk_logits")
        return np.asarray(logits)  # mxlint: allow-host-sync


class LlamaServer:
    """Continuous-batching inference server over an AOT serving bundle.

    ``LlamaServer(path).start()`` then ``submit(prompt) -> Request`` /
    ``generate(prompt) -> tokens``.  Geometry validation happens at
    load (``expect_geometry`` pins fields); admission backpressure
    raises ``ServeQueueFull``.

    ``spec_k`` picks the runtime speculation width (default: whatever
    the bundle was compiled with; 0 turns it off).  ``kv_dtype`` is an
    assertion, not a conversion — pass it to refuse a bundle whose
    arena dtype isn't what the deployment expects.

    Robustness (ISSUE 15, docs/serving.md "Robustness & deploys"): the
    loop is crash-contained (a step exception fails only the affected
    requests with :class:`ServeInternalError`, dumps the flight
    recorder, flips ``/healthz`` ``ok`` and restarts the loop over a
    reset arena), ``drain()``/SIGTERM stops admission and gives
    in-flight work ``MXNET_SERVE_DRAIN_TIMEOUT`` to finish, and
    ``reload(bundle)`` hot-swaps the executables + arena at a step
    boundary without dropping a request.
    """

    def __init__(self, bundle_path, expect_geometry=None, queue_depth=None,
                 sampler=None, spec_k=None, kv_dtype=None):
        from .model import check_geometry, load_serving_executables

        geometry, exes = load_serving_executables(
            bundle_path, expect=expect_geometry)
        if kv_dtype is not None:
            check_geometry(geometry, {"kv_dtype": str(kv_dtype)},
                           origin=bundle_path)
        arena = PagedKVArena(geometry)
        self._init_core(AOTRunner(exes, arena), arena,
                        queue_depth=queue_depth, sampler=sampler,
                        spec_k=spec_k)
        self.bundle_path = bundle_path
        self.bundle_sha = _bundle_sha(bundle_path)

    def _init_core(self, runner, arena, queue_depth=None, sampler=None,
                   spec_k=None, clock=time.monotonic):
        self.geometry = arena.geometry
        self.arena = arena
        self.runner = runner
        self.scheduler = Scheduler(runner, arena, queue_depth=queue_depth,
                                   sampler=sampler, spec_k=spec_k,
                                   clock=clock)
        self.bundle_path = None
        self.bundle_sha = None
        self.server_id = "srv-%x-%x" % (os.getpid(), next(_SERVER_IDS))
        self._start_t = time.monotonic()
        self._stop = threading.Event()
        self._thread = None
        self._res_thread = None       # rescheck token for the loop thread
        self._http = None
        self._healthy = True          # flips (sticky) on loop death
        self._last_loop_error = None
        self._loop_restarts = 0
        self._loop_steps = 0
        self._draining = False
        self._swap_lock = _lockcheck.named_lock("serve.swap")
        self._pending_swap = None     # (geometry, runner, arena, path, evt)
        self._max_restarts = _env_int("MXNET_SERVE_LOOP_MAX_RESTARTS", 16)

    @classmethod
    def from_parts(cls, runner, arena, queue_depth=None, sampler=None,
                   spec_k=None, clock=time.monotonic):
        """Assemble a server around an existing runner + arena, no
        bundle load — the seam the serve-chaos suite drives with
        scripted runners and an injected clock (the loop machinery —
        containment, drain, hot-swap — is exactly the production
        path)."""
        self = cls.__new__(cls)
        self._init_core(runner, arena, queue_depth=queue_depth,
                        sampler=sampler, spec_k=spec_k, clock=clock)
        return self

    # -- lifecycle --------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        # a previous stop() closed the submit window; reopen it (a
        # loop-gave-up refusal is NOT a ServeShutdown and stays sticky)
        if isinstance(self.scheduler._refuse_error, ServeShutdown):
            self.scheduler.refuse(None)
        self._thread = threading.Thread(target=self._loop,
                                        name="mxnet-serve", daemon=True)
        self._thread.start()
        self._res_thread = _rescheck.acquire("thread", "mxnet-serve",
                                             scope="serve:%x" % id(self))
        return self

    def _loop(self):
        while not self._stop.is_set():
            if not self._loop_tick():
                self.scheduler.wait_for_work(0.005)

    def _loop_tick(self):
        """One crash-contained scheduler round (False = idle).  Tests
        drive this synchronously; the background thread just loops it."""
        try:
            _faults.maybe_inject("serve_step", step=self._loop_steps)
            self._loop_steps += 1
            self._maybe_swap()
            return self.scheduler.step()
        except Exception as e:  # noqa: BLE001 — containment IS the point
            self._contain_loop_failure(e)
            return True

    def _contain_loop_failure(self, exc):
        """An unexpected step exception must not kill the serve thread
        silently (the pre-PR failure mode: every pending future hung
        until client timeout).  Fail the affected requests typed, dump
        the flight recorder, mark /healthz not-ok, reset the arena and
        keep serving — up to MXNET_SERVE_LOOP_MAX_RESTARTS, after which
        submits are refused fast instead of queueing into a dead loop."""
        self._healthy = False
        self._last_loop_error = "%s: %s" % (type(exc).__name__, exc)
        _flight.record("serve.loop_died", error=type(exc).__name__,
                       detail=str(exc)[:200])
        _flight.crash_dump("serve_loop:%s" % type(exc).__name__)
        failed = self.scheduler.fail_all(ServeInternalError(
            "serve loop died (%s: %s) — request failed, loop restarting"
            % (type(exc).__name__, exc)), status="failed")
        self._loop_restarts += 1
        if _metrics.enabled():
            _metrics.counter(
                "mxnet_serve_loop_restarts_total",
                help="serve-loop restarts after a contained crash").inc()
        try:
            self.arena.reset()
        except Exception as e2:
            # a poisoned arena that cannot even reset means no future
            # request can be served correctly: refuse, stop, stay not-ok
            err = ServeInternalError(
                "serve loop died and the arena failed to reset (%s) — "
                "server is down" % e2)
            self.scheduler.refuse(err)
            self.scheduler.fail_all(err, status="failed")
            self._stop.set()
            _flight.record("serve.loop_gave_up", restarts=self._loop_restarts)
            return
        _flight.record("serve.loop_restart", n=self._loop_restarts,
                       failed=failed)
        if self._loop_restarts >= self._max_restarts:
            err = ServeInternalError(
                "serve loop died %d times (MXNET_SERVE_LOOP_MAX_RESTARTS"
                "=%d) — giving up; last error: %s"
                % (self._loop_restarts, self._max_restarts,
                   self._last_loop_error))
            self.scheduler.refuse(err)
            self.scheduler.fail_all(err, status="failed")
            self._stop.set()
            _flight.record("serve.loop_gave_up", restarts=self._loop_restarts)

    def stop(self):
        self._stop.set()
        # close the submit window BEFORE the straggler sweep: a submit
        # racing the has_work() check below would otherwise queue a
        # future nobody ever resolves (the loop is gone and fail_all
        # already ran) — with the refusal set it fails typed instead.
        # start() reopens the window.
        self.scheduler.refuse(
            ServeShutdown("server is stopped — not accepting requests"))
        self.scheduler.kick()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        _rescheck.release(self._res_thread)
        self._res_thread = None
        with self._swap_lock:
            self._pending_swap = None  # a waiting reload() times out
        # never abandon futures (ISSUE 15 satellite): anything still
        # queued or in flight fails typed instead of hanging clients
        if self.scheduler.has_work():
            self.scheduler.fail_all(
                ServeShutdown("server stopped with the request still "
                              "queued or in flight"), status="drained")
        if self._http is not None:
            self._http.shutdown()
            self._http = None
        # shared pages (prefix cache, pinned sessions) are not "work" —
        # flush them explicitly or the quiescence asserts below see them
        self.scheduler.release_shared()
        if _rescheck.enabled():
            # the every-handle-kind generalization of
            # arena.assert_quiescent(): no live futures, no live pages
            _rescheck.assert_quiescent(scope=self.scheduler.res_scope)
            _rescheck.assert_quiescent(scope=self.arena.res_scope)

    def drain(self, timeout=None):
        """Graceful shutdown, phase 1: stop admission (new submits get
        503 + Retry-After), let queued + in-flight work finish within
        ``timeout`` (default ``MXNET_SERVE_DRAIN_TIMEOUT``), then fail
        stragglers with :class:`ServeShutdown`.  Returns the straggler
        count (0 = clean drain).  Call ``stop()`` after."""
        if timeout is None:
            timeout = _env_float("MXNET_SERVE_DRAIN_TIMEOUT", 30.0)
        timeout = float(timeout)
        self._draining = True
        self.scheduler.drain()
        _flight.record("serve.drain", timeout_s=timeout,
                       queued=self.scheduler.queue_len(),
                       active=self.scheduler.active_slots())
        deadline = time.monotonic() + timeout
        while self.scheduler.has_work() and time.monotonic() < deadline:
            if self._thread is None:
                if not self.scheduler.step():
                    break  # no loop and no progress possible
            else:
                time.sleep(0.005)
        self.scheduler.hold_admission(True)
        stragglers = 0
        if self.scheduler.has_work():
            stragglers = self.scheduler.fail_all(ServeShutdown(
                "drain timed out after %.1fs "
                "(MXNET_SERVE_DRAIN_TIMEOUT) with the request still "
                "queued or in flight" % timeout), status="drained")
        _flight.record("serve.drained", stragglers=stragglers)
        # in-flight turns are finished (or failed) by now: unpin every
        # session and drop the prefix cache so the arena reaches true
        # quiescence — a drained server holds zero pages
        self.scheduler.release_shared()
        if _rescheck.enabled():
            _rescheck.assert_quiescent(scope=self.scheduler.res_scope)
            _rescheck.assert_quiescent(scope=self.arena.res_scope)
        return stragglers

    # -- bundle hot-swap --------------------------------------------------
    def reload(self, bundle_path, timeout=60):
        """Hot-swap to a new serving bundle with zero dropped requests
        and zero live jits: deserialize the MXAOT1 executables on the
        CALLING thread (the loop keeps serving), pin the geometry fields
        live traffic depends on (``KVGeometry.hot_swap_pins``), then
        hand runner + fresh arena to the loop, which swaps them at the
        first step boundary with no active lanes — in-flight requests
        finish on the old executables, queued requests wait (admission
        held, never dropped) and prefill into the new arena."""
        from .model import check_geometry, load_serving_executables

        g2, exes2 = load_serving_executables(bundle_path)
        check_geometry(g2, self.geometry.hot_swap_pins(),
                       origin=bundle_path)
        arena2 = PagedKVArena(g2)
        runner2 = AOTRunner(exes2, arena2)
        done = threading.Event()
        with self._swap_lock:
            if self._pending_swap is not None:
                raise MXNetError("a reload is already in flight")
            self._pending_swap = (g2, runner2, arena2, bundle_path, done)
        if self._thread is None:
            # no background loop: drain lanes and swap on this thread
            while self.scheduler.active_slots():
                self.scheduler.step()
            self._maybe_swap()
        else:
            self.scheduler.kick()
            if not done.wait(timeout):
                with self._swap_lock:
                    self._pending_swap = None
                self.scheduler.hold_admission(False)
                raise MXNetError(
                    "reload of %r timed out after %ss (loop stalled or "
                    "lanes never drained)" % (bundle_path, timeout))
        return self

    def _maybe_swap(self):
        """Loop-side half of ``reload()``: runs at every step boundary,
        holds admission while old lanes drain, then swaps atomically."""
        with self._swap_lock:
            if self._pending_swap is None:
                return
        self.scheduler.hold_admission(True)
        if self.scheduler.active_slots():
            return  # old lanes still decoding on the old runner
        with self._swap_lock:
            pend = self._pending_swap
            if pend is None:  # reload() timed out and withdrew
                self.scheduler.hold_admission(False)
                return
            self._pending_swap = None
        g2, runner2, arena2, path, done = pend
        self.scheduler.swap(runner2, arena2)
        self.geometry, self.runner, self.arena = g2, runner2, arena2
        self.bundle_path = path
        self.bundle_sha = _bundle_sha(path)
        self.scheduler.hold_admission(False)
        if _metrics.enabled():
            _metrics.counter(
                "mxnet_serve_reloads_total",
                help="bundle hot-swaps completed").inc()
        _flight.record("serve.reload", bundle=str(path))
        done.set()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- request surface --------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_s=None, session=None, trace_id=None):
        """Enqueue; returns the Request future (``.result(timeout)``).
        ``session`` is a session id from :meth:`open_session` — the turn
        prefills only its delta on top of the pinned history.
        ``trace_id`` overrides the self-minted id (the FleetRouter's
        fleet trace id, or an ``X-MXNet-Trace`` header value)."""
        if self._thread is None:
            raise MXNetError("server not started — call start() first")
        return self.scheduler.submit(
            Request(prompt, max_new_tokens=max_new_tokens, eos_id=eos_id,
                    deadline_s=deadline_s, session_id=session,
                    trace_id=trace_id))

    def generate(self, prompt, max_new_tokens=None, eos_id=None,
                 timeout=300, deadline_s=None, session=None):
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           eos_id=eos_id, deadline_s=deadline_s,
                           session=session).result(timeout)

    def open_session(self):
        """Create a pinned multi-turn chat session; returns its id."""
        return self.scheduler.open_session()

    def close_session(self, session_id):
        """Unpin a session's pages; True if it existed."""
        return self.scheduler.close_session(session_id)

    def cancel(self, trace_id):
        """Cancel a queued or in-flight request by trace id (the HTTP
        front's ``DELETE /v1/generate/<id>``); True if it was found."""
        return self.scheduler.cancel(trace_id)

    def stats(self):
        return self.scheduler.stats()

    def healthy(self):
        """Readiness: False once the loop has died (sticky — the flight
        dump names why) or while draining — the signal a load balancer
        routes away on."""
        return self._healthy and not self._draining

    def healthz(self):
        """The GET /healthz body: scheduler stats plus the operational
        signals an external prober actually pages on — arena pressure,
        queue depth, live device memory, flight-recorder state."""
        st = self.scheduler.stats()
        try:
            by_origin, total = _memdump.refresh()
        except Exception:           # health must not 500 on accounting
            by_origin, total = {}, 0
        st.update({
            "ok": self.healthy(),
            "draining": self._draining,
            "bundle_sha": self.bundle_sha,
            "server_id": self.server_id,
            "uptime_s": round(time.monotonic() - self._start_t, 3),
            "loop_restarts": self._loop_restarts,
            "last_loop_error": self._last_loop_error,
            "queue_depth": st["queue_len"],
            "live_device_bytes": total,
            "device_bytes_by_origin": by_origin,
            "peak_device_bytes": _memdump.peak_bytes(),
            "flight": _flight.status(),
            "membership": self._membership_health(),
        })
        return st

    @staticmethod
    def _membership_health():
        """Elastic-membership view from the metrics registry (zeros when
        this process hosts no kvstore shard): the prober that pages on
        queue depth also sees roster shrink without scraping /metrics."""
        from ..telemetry import metrics as _metrics

        snap = _metrics.snapshot()

        def val(fam, default=0):
            series = snap.get(fam, {}).get("series", [])
            return series[0].get("value", default) if series else default

        return {
            "epoch": int(val("mxnet_membership_epoch")),
            "ranks_active": int(val("mxnet_ranks_active")),
            "evictions_total": int(sum(
                s.get("value", 0) for s in
                snap.get("mxnet_rank_evictions_total",
                         {}).get("series", []))),
        }

    # -- naive baseline (bench comparison) --------------------------------
    def static_generate(self, requests):
        """Static batching: groups of ``max_batch``, no admission between
        steps, each group decodes until its SLOWEST member finishes.
        Returns the token lists in request order.  Runs on the caller's
        thread — stop() the background loop first or don't start() it.
        """
        g = self.geometry
        sched = self.scheduler
        out = []
        for base in range(0, len(requests), g.max_batch):
            group = requests[base: base + g.max_batch]
            slots = []
            for req in group:
                pages = self.arena.alloc(
                    self.arena.pages_needed(
                        len(req.prompt) + req.max_new_tokens), req.rid)
                if pages is None:
                    # earlier members of this group already hold pages —
                    # give them back or the arena leaks them for good
                    for prev, prev_pages, _ in slots:
                        self.arena.free(prev_pages, owner=prev.rid)
                    raise MXNetError("arena too small for a static batch")
                row = self.arena.block_row(pages)
                logits = self.runner.prefill(
                    sched.pick_bucket(len(req.prompt)),
                    np.asarray(req.prompt, dtype=np.int32),
                    len(req.prompt), row)
                req.tokens.append(sched.sampler(logits, req))
                slots.append((req, pages, row))
            # the whole group decodes in lockstep until every member is
            # done — finished lanes keep burning a slot (that waste IS
            # the baseline being measured)
            def _busy(req):
                if len(req.tokens) >= req.max_new_tokens:
                    return False
                return not (req.eos_id is not None
                            and req.tokens[-1] == req.eos_id)
            while any(_busy(req) for req, _, _ in slots):
                tokens = np.zeros(g.max_batch, dtype=np.int32)
                positions = np.zeros(g.max_batch, dtype=np.int32)
                tables = np.zeros((g.max_batch, g.max_pages_per_seq),
                                  dtype=np.int32)
                for i, (req, _, row) in enumerate(slots):
                    tokens[i] = req.tokens[-1]
                    positions[i] = len(req.prompt) + len(req.tokens) - 1
                    tables[i] = row
                logits = self.runner.decode(tokens, positions, tables)
                for i, (req, _, _) in enumerate(slots):
                    if _busy(req):
                        req.tokens.append(sched.sampler(logits[i], req))
            for req, pages, _ in slots:
                self.arena.free(pages, owner=req.rid)
                out.append(list(req.tokens))
        return out

    # -- HTTP front -------------------------------------------------------
    def serve_http(self, port=0, host="127.0.0.1"):
        """Minimal stdlib HTTP front (POST /v1/generate, POST /v1/chat,
        GET /metrics, GET /metrics.json, GET /healthz,
        GET /v1/trace/<id>, DELETE /v1/generate/<id>,
        DELETE /v1/chat/<id>).  Returns the bound (host, port).
        A POST may carry an ``X-MXNet-Trace`` header (the FleetRouter's
        fleet trace id): it becomes the request's ``trace_id``, so
        router and replica flight events correlate on one id.

        Status mapping (ISSUE 15): draining / queue-full → 503 with a
        ``Retry-After`` header derived from queue depth × decode-pace
        EMA; deadline exceeded → 504; cancelled → 409; shutdown /
        internal → 503; anything else → 500.  /healthz returns 503 once
        the loop has died or while draining, so probers flip without
        parsing the body."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        def _error_code(err):
            if isinstance(err, ServeDeadlineExceeded):
                return 504
            if isinstance(err, ServeSessionUnknown):
                return 404
            if isinstance(err, (ServeCancelled, ServeSessionBusy)):
                return 409
            if isinstance(err, (ServeShutdown, ServeInternalError,
                                ServeDraining, ServeQueueFull)):
                return 503
            return 500

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet: telemetry is the record
                pass

            def _send(self, code, body, ctype="application/json",
                      headers=None):
                payload = body.encode() if isinstance(body, str) \
                    else json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def _await_or_cancel(self, req, timeout):
                """Wait for the result while watching the client socket:
                a connection closed mid-decode cancels the request (its
                pages free at the next step boundary) instead of burning
                decode steps for a reader that is gone.  True = settled
                or timed out, False = client disconnected."""
                t_end = time.monotonic() + timeout
                while not req._done.wait(0.05):
                    if time.monotonic() >= t_end:
                        return True
                    try:
                        r, _, _ = select.select([self.connection], [], [],
                                                0)
                        gone = bool(r) and self.connection.recv(
                            1, socket.MSG_PEEK) == b""
                    except (OSError, ValueError):
                        gone = True
                    if gone:
                        server.cancel(req.trace_id)
                        return False
                return True

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, _metrics.prometheus_text(),
                               ctype="text/plain; version=0.0.4")
                elif self.path == "/metrics.json":
                    # full registry snapshot — the fleet aggregator's
                    # scrape format (labels survive as structure, not
                    # re-parsed exposition text)
                    self._send(200, _metrics.snapshot())
                elif self.path == "/healthz":
                    body = server.healthz()
                    if body["ok"]:
                        self._send(200, body)
                    else:
                        # not-ok/draining 503s back off external load
                        # balancers exactly like queue-full ones do
                        self._send(503, body, headers={
                            "Retry-After": _retry_after_header(
                                server.scheduler.retry_after_s())})
                elif self.path.startswith("/v1/trace/"):
                    tid = self.path[len("/v1/trace/"):]
                    tr = server.scheduler.trace(tid)
                    if tr is None:
                        self._send(404, {"error": "unknown trace id %r "
                                                  "(evicted or never seen)"
                                                  % tid})
                    else:
                        self._send(200, tr)
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                if self.path not in ("/v1/generate", "/v1/chat"):
                    self._send(404, {"error": "not found"})
                    return
                chat = self.path == "/v1/chat"
                sid = None
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    doc = json.loads(self.rfile.read(n) or b"{}")
                    if chat:
                        # no "session" field = first turn: open one and
                        # return its id so the client can keep it warm
                        sid = doc.get("session") or server.open_session()
                    req = server.submit(
                        doc["prompt"],
                        max_new_tokens=doc.get("max_new_tokens"),
                        eos_id=doc.get("eos_id"),
                        deadline_s=doc.get("deadline_s"),
                        session=sid,
                        trace_id=self.headers.get("X-MXNet-Trace"))
                except ServeSessionUnknown as e:
                    self._send(404, {"error": str(e)})
                    return
                except ServeSessionBusy as e:
                    self._send(409, {"error": str(e)})
                    return
                except (ServeDraining, ServeQueueFull) as e:
                    self._send(503, {"error": str(e)},
                               headers={"Retry-After": _retry_after_header(
                                   getattr(e, "retry_after_s", 1))})
                    return
                except ServeInternalError as e:  # loop gave up: refusing
                    self._send(503, {"error": str(e)})
                    return
                except (MXNetError, KeyError, ValueError) as e:
                    self._send(400, {"error": str(e)})
                    return
                if req.done() and req.error is not None:
                    # rejected at submit (prompt over the bucket ladder,
                    # budget over max context): client error, not a 500
                    self._send(400, {"error": str(req.error)})
                    return
                if not self._await_or_cancel(req,
                                             doc.get("timeout", 300)):
                    return  # client went away: request cancelled
                try:
                    tokens = req.result(timeout=0.001)
                except MXNetError as e:
                    self._send(_error_code(req.error or e),
                               {"error": str(e),
                                "trace_id": req.trace_id,
                                "session": sid} if chat else
                               {"error": str(e),
                                "trace_id": req.trace_id})
                    return
                body = {"tokens": tokens,
                        "ttft_s": req.ttft,
                        "trace_id": req.trace_id,
                        "breakdown": req.breakdown()}
                if chat:
                    body["session"] = sid
                self._send(200, body)

            def do_DELETE(self):
                if self.path.startswith("/v1/chat/"):
                    sid = self.path[len("/v1/chat/"):]
                    try:
                        closed = server.close_session(sid)
                    except ServeSessionBusy as e:
                        self._send(409, {"error": str(e)})
                        return
                    if closed:
                        self._send(200, {"closed": sid})
                    else:
                        self._send(404, {"error": "no session %r "
                                                  "(expired or never "
                                                  "opened)" % sid})
                    return
                if not self.path.startswith("/v1/generate/"):
                    self._send(404, {"error": "not found"})
                    return
                tid = self.path[len("/v1/generate/"):]
                if server.cancel(tid):
                    self._send(200, {"cancelled": tid})
                else:
                    self._send(404, {"error": "no queued or in-flight "
                                              "request with trace id %r"
                                              % tid})

        self._http = ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._http.serve_forever,
                         name="mxnet-serve-http", daemon=True).start()
        return self._http.server_address


def poisson_workload(n_requests, rate_rps, prompt_range, max_new_range,
                     vocab_size, seed=0, eos_id=None):
    """Seeded mixed-length Poisson workload: ``[(arrival_s, Request)]``.

    Prompt lengths draw uniform over ``prompt_range``; generation budgets
    draw a geometric-ish heavy tail clipped to ``max_new_range`` — the
    length spread is what separates continuous batching from the static
    baseline (a static batch runs at the pace of its slowest member).
    """
    rng = np.random.default_rng(seed)
    lo_p, hi_p = prompt_range
    lo_n, hi_n = max_new_range
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n_requests))
    out = []
    for i in range(n_requests):
        plen = int(rng.integers(lo_p, hi_p + 1))
        budget = int(np.clip(lo_n + rng.geometric(
            2.0 / (lo_n + hi_n)), lo_n, hi_n))
        prompt = rng.integers(0, vocab_size, size=plen).tolist()
        out.append((float(arrivals[i]),
                    Request(prompt, max_new_tokens=budget, eos_id=eos_id)))
    return out


def drive_workload(server, workload, timeout=600, clock=time.monotonic,
                   sleep=time.sleep):
    """Replay a :func:`poisson_workload` against a started server.

    Returns ``(requests, wall_seconds)`` — wall time from first submit to
    last completion.  Used by the serving bench and the serve-smoke CI
    job (which passes a null ``sleep`` to hammer the queue).
    """
    t0 = clock()
    reqs = []
    for arrival, req in workload:
        lag = arrival - (clock() - t0)
        if lag > 0:
            sleep(lag)
        try:
            server.scheduler.submit(req)
        except MXNetError as e:  # queue-full backpressure: shed, record
            if req.error is None:
                req.error = e
            req._done.set()
        reqs.append(req)
    for req in reqs:
        try:
            req.result(timeout=timeout)
        except MXNetError:
            pass  # rejected/failed requests surface via req.error
    return reqs, clock() - t0
