"""Production inference serving tier (docs/serving.md).

Three coupled pieces (ISSUE 8 tentpole):

- :mod:`.scheduler` — async continuous batching: bounded admission
  queue with backpressure, prefill/decode split over bucketed sequence
  lengths, slot recycling on EOS;
- :mod:`.arena` — paged KV-cache arena: block tables over fixed-size
  KV pages held as NDArrays, reuse gated on the engine's
  var-dependency tracking (``Engine.pending_reads``);
- :mod:`.model` — AOT-compiled paged prefill/decode executables in a
  PR 7 ``MXAOT1`` bundle, so a serving process performs zero live jits.

ISSUE 13 stacked two decode multipliers on top: n-gram self-speculative
decoding (:mod:`.spec` proposes drafts, the bundle's compiled ``verify``
signature scores them, acceptance is exact so greedy output is identical
with speculation on or off) and an int8 paged-KV arena with per-page
quantization scales (``export_serving_bundle(..., kv_dtype="int8",
spec_k=4)``).

ISSUE 15 closed the request lifecycle under failure (docs/serving.md
"Robustness & deploys"): per-request deadlines and cancellation with
typed errors, graceful drain (503 + Retry-After), AOT bundle hot-swap
(``LlamaServer.reload``), serve-loop crash containment, and seeded
chaos coverage (``tests/test_serve_chaos.py``).

ISSUE 19 added cross-request KV reuse (docs/serving.md "Prefix caching,
sessions & chunked prefill"): a radix-tree :class:`PrefixCache` splices
already-prefilled prompt pages into new requests' block tables
(refcounted sharing over the arena's owner-checked free list), pinned
multi-turn chat sessions (``POST /v1/chat``) that prefill only each
turn's delta, and chunked prefill (``prefill_chunk``) that interleaves
long prompts with decode steps — greedy output stays token-for-token
identical cache-on vs cache-off.

ISSUE 18 lifted those per-replica primitives to a fleet
(:mod:`.fleet`, docs/serving.md "Fleet serving"): a
:class:`FleetRouter` HTTP front over N replicas with queue-depth-aware
power-of-two-choices routing, bounded retries + opt-in hedging,
circuit-breaker ejection/re-admission, and chaos-verified
``rolling_deploy`` with zero dropped requests.

Quick start::

    from mxnet_tpu import serve
    from mxnet_tpu.gluon.model_zoo.llama import llama_small

    net = llama_small(); net.initialize()
    serve.export_serving_bundle(net, "llama.mxaot",
                                page_size=8, num_pages=64, max_batch=4,
                                prefill_buckets=(16, 32))
    with serve.LlamaServer("llama.mxaot") as srv:
        tokens = srv.generate([1, 2, 3], max_new_tokens=16)
"""
from .arena import PagedKVArena
from .fleet import (FleetNoHealthyReplica, FleetRouter, HttpReplica,
                    LocalReplica, fleet_drive_workload)
from .model import (KVGeometry, check_geometry, export_serving_bundle,
                    geometry_from_net, load_serving_executables)
from .prefix import PrefixCache
from .scheduler import (Request, Scheduler, ServeCancelled,
                        ServeDeadlineExceeded, ServeDraining,
                        ServeInternalError, ServeQueueFull,
                        ServeSessionBusy, ServeSessionUnknown, ServeShutdown,
                        clamp_retry_after, greedy_sampler)
from .server import (AOTRunner, LlamaServer, drive_workload,
                     poisson_workload)
from .spec import NgramProposer, propose_ngram

__all__ = [
    "AOTRunner", "FleetNoHealthyReplica", "FleetRouter", "HttpReplica",
    "KVGeometry", "LlamaServer", "LocalReplica", "NgramProposer",
    "PagedKVArena", "PrefixCache", "Request",
    "Scheduler", "ServeCancelled", "ServeDeadlineExceeded",
    "ServeDraining", "ServeInternalError", "ServeQueueFull",
    "ServeSessionBusy", "ServeSessionUnknown",
    "ServeShutdown", "check_geometry", "clamp_retry_after",
    "drive_workload", "export_serving_bundle", "fleet_drive_workload",
    "geometry_from_net", "greedy_sampler",
    "load_serving_executables", "poisson_workload", "propose_ngram",
]
