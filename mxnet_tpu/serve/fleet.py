"""FleetRouter: a queue-aware HTTP front over N LlamaServer replicas.

One `LlamaServer` is one failure domain: a loop crash, a slow decode
pace, or a bundle deploy takes every request with it.  This module makes
the *fleet* strictly more robust than any one replica (ROADMAP item
1(c)) with four pillars, all built on the per-replica primitives PR 15
shipped (drain + Retry-After, ``reload()`` hot-swap, sticky not-ok
``/healthz``, ``DELETE /v1/generate/<id>`` cancellation):

* **Queue-depth-aware routing.**  A background prober polls every
  replica's ``/healthz`` (interval ``MXNET_FLEET_PROBE_INTERVAL``) and
  feeds a power-of-two-choices picker: sample two candidates, score each
  by ``(queue_depth + router in-flight) x TPOT pace``, route to the
  lower.  Two random choices beat both round-robin (ignores load) and
  global-minimum (herds onto one replica between probes).  A replica's
  ``Retry-After`` hint gates it out of the candidate set until the hint
  expires.  ``submit(session=...)`` carries an ISSUE 19 chat-session
  affinity hint: the turn prefers the replica whose arena pins the
  session's pages (routing it anywhere else guarantees a
  ``ServeSessionUnknown``), falling back to p2c when that replica is
  ejected, draining, or gated.

* **Bounded retries + hedging.**  Submit-time refusals (queue full,
  draining, dead loop, connection errors) retry on a *different*
  replica with the PR 3 backoff discipline — ``base * 2^k`` capped at
  5 s, +-25 % jitter (``MXNET_FLEET_RETRIES``/``MXNET_FLEET_BACKOFF``).
  Mid-flight failures retry only for idempotent requests (greedy
  generation is; a sampled request replayed elsewhere is a different
  request).  Opt-in hedging (``MXNET_FLEET_HEDGE``) fires a second
  attempt on another replica after a p99-derived delay; the first
  winner cancels the loser via the replica's cancellation surface.  The
  client deadline is *decremented* across attempts and propagated, so a
  retry can never resurrect an expired request.

* **Replica lifecycle.**  ``MXNET_FLEET_EJECT_AFTER`` consecutive bad
  probes (exception, or a sticky not-ok body) eject a replica — a
  per-replica circuit breaker.  After ``MXNET_FLEET_READMIT_AFTER``
  seconds the breaker goes half-open: one probe is allowed through, and
  a healthy answer re-admits the replica.  ``rolling_deploy(bundle)``
  walks the fleet one replica at a time — steer traffic away, drain +
  ``reload()`` at a step boundary (PR 15's zero-dropped-requests swap),
  re-probe, re-admit — and raises unless every replica converged to the
  same ``bundle_sha`` (the ``/healthz`` field added for exactly this).

* **Chaos verification.**  Five injection sites
  (``fleet_probe``/``fleet_forward`` on the router side,
  ``replica_kill``/``replica_hang``/``replica_slow`` on the forward
  path into a replica) drive the seeded deterministic matrix in
  ``tests/test_fleet_chaos.py`` — run twice per seed, asserting
  identical outcomes, every non-doomed request completed typed, and
  leak-free arenas on every replica.

Telemetry: ``mxnet_fleet_requests_total{replica,status}``,
``mxnet_fleet_retries_total{reason}``,
``mxnet_fleet_hedges_total{outcome}``,
``mxnet_fleet_ejections_total{replica,reason}``,
``mxnet_fleet_replicas_healthy``, ``mxnet_fleet_route_queue_depth``,
plus ``fleet.*`` flight events (retry/hedge/eject/readmit/deploy).

The fleet observability plane (ISSUE 20, docs/observability.md "Fleet
observability") rides on three seams here:

* **Distributed tracing.**  ``submit()`` mints a fleet trace id
  (``f<pid>-<n>``) that replicas stamp into ``Request.trace_id``
  (in-process) or receive via an ``X-MXNet-Trace`` header (HTTP), so
  one id correlates router and replica flight events.  Every attempt —
  retry, hedge, cancellation-of-loser — records an attributed
  ``fleet.attempt``/``fleet.hedge``/``fleet.cancel`` event (attempt
  index, replica, role, duration), and ``GET /v1/trace/<id>`` prepends
  the routing breakdown to the owning replica's stored trace.
* **Metric aggregation.**  The prober scrapes each replica's metrics
  every ``MXNET_FLEET_METRICS_EVERY``-th probe;
  ``fleet_metrics_snapshot()`` merges them via
  ``telemetry.aggregate`` (counters sum, gauges keep per-replica
  series, histograms merge bucket-wise) and the fleet ``GET /metrics``
  serves the merged exposition.
* **SLO engine.**  ``attach_slo()`` (or ``MXNET_FLEET_SLO``) evaluates
  declarative objectives over the aggregated stream each probe sweep;
  with ``MXNET_FLEET_SLO_SHED`` the fast-window burn alert sheds
  optional work — hedging turns off until the alert clears.

Replicas can be in-process ``LlamaServer`` objects (the bench and chaos
matrix run 3 in one process) or ``http://host:port`` bases fronting
remote servers; both hide behind the same probe/submit/cancel surface.
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import random
import threading
import time
import urllib.error
import urllib.request

from ..base import MXNetError, env_flag
from ..telemetry import aggregate as _aggregate
from ..telemetry import flight as _flight
from ..telemetry import metrics as _metrics
from ..telemetry import slo as _slo
from ..testing import faults as _faults
from ..testing import lockcheck as _lockcheck
from ..testing import rescheck as _rescheck
from .scheduler import (Request, ServeCancelled, ServeDeadlineExceeded,
                        ServeDraining, ServeInternalError, ServeQueueFull,
                        ServeSessionBusy, ServeSessionUnknown, ServeShutdown,
                        _env_float, _env_int, clamp_retry_after)

__all__ = [
    "FleetRouter", "FleetNoHealthyReplica", "LocalReplica", "HttpReplica",
    "fleet_drive_workload",
]

_BACKOFF_CAP_S = 5.0      # same ceiling as the kvstore retry discipline
_ROUTE_DEPTH_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64)


class FleetNoHealthyReplica(MXNetError):
    """Every replica is ejected, draining, or gated by a Retry-After
    hint.  Carries ``retry_after_s`` so the HTTP front can tell the
    caller when trying again is worthwhile."""

    retry_after_s = 1.0


# ---------------------------------------------------------------------------
# replica adapters: one probe/submit/cancel/reload surface, two transports
# ---------------------------------------------------------------------------

class _LocalHandle:
    """An in-flight request on an in-process replica (wraps the
    scheduler's ``Request`` future)."""

    def __init__(self, replica, req):
        self._replica = replica
        self.req = req

    @property
    def trace_id(self):
        return self.req.trace_id

    @property
    def error(self):
        return self.req.error

    @property
    def ttft(self):
        return self.req.ttft

    def wait(self, timeout):
        return self.req._done.wait(timeout)

    def done(self):
        return self.req.done()

    def result(self, timeout):
        return self.req.result(timeout)

    def cancel(self):
        return self._replica.cancel(self.req.trace_id)


class _HungHandle:
    """The deterministic stand-in for a replica that accepted a request
    and then went silent (``replica_hang``): never completes, cancel is
    a no-op — the hedge path's reason to exist."""

    trace_id = None
    error = None
    ttft = None

    def __init__(self, replica_name):
        self._replica_name = replica_name
        self._never = threading.Event()

    def wait(self, timeout):
        return self._never.wait(timeout)

    def done(self):
        return False

    def result(self, timeout):
        self._never.wait(timeout)
        raise ServeInternalError(
            "request hung on replica %s (fault-injected) and no hedge "
            "completed it" % self._replica_name)

    def cancel(self):
        return True


class LocalReplica:
    """An in-process ``LlamaServer`` behind the replica surface.

    ``reload_fn`` is the chaos seam: ``from_parts`` servers have no
    bundle file to load, so the fleet-chaos matrix substitutes a
    scripted hot-swap (same ``_pending_swap`` machinery, no disk)."""

    def __init__(self, server, name=None, reload_fn=None):
        self.server = server
        self.name = name or getattr(server, "server_id", None) or \
            "r%x" % id(server)
        self._reload_fn = reload_fn

    def probe(self):
        return self.server.healthz()

    def metrics(self):
        """Per-replica metrics scrape.  In-process replicas share ONE
        registry, so scraping it per replica would multiply every count
        by N — the per-server scheduler aggregates are the only honest
        per-replica numbers here (``aggregate.snapshot_from_stats``)."""
        return _aggregate.snapshot_from_stats(self.server.stats())

    def trace(self, trace_id):
        return self.server.scheduler.trace(trace_id)

    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_s=None, session=None, trace_id=None):
        _faults.maybe_inject("replica_slow", replica=self.name)
        try:
            _faults.maybe_inject("replica_kill", replica=self.name)
        except _faults.LoopKilled as e:
            # the replica "process" dies: in-flight work fails typed via
            # the loop-crash containment path, healthz flips sticky
            # not-ok, and the router sees a dead transport
            self.server._contain_loop_failure(e)
            raise ConnectionResetError(
                "replica %s died (%s)" % (self.name, e))
        try:
            _faults.maybe_inject("replica_hang", replica=self.name)
        except _faults.FaultInjected:
            return _HungHandle(self.name)
        req = self.server.scheduler.submit(
            Request(prompt, max_new_tokens=max_new_tokens, eos_id=eos_id,
                    deadline_s=deadline_s, session_id=session,
                    trace_id=trace_id))
        return _LocalHandle(self, req)

    def cancel(self, trace_id):
        if trace_id is None:
            return False
        return self.server.scheduler.cancel(trace_id)

    def reload(self, bundle_path, timeout=60):
        if self._reload_fn is not None:
            return self._reload_fn(bundle_path, timeout)
        return self.server.reload(bundle_path, timeout=timeout)


class _HttpHandle:
    """An in-flight request on a remote replica: one daemon thread owns
    the blocking POST; the handle mirrors the Request-future surface."""

    def __init__(self, replica, doc, timeout, path="/v1/generate",
                 fleet_trace_id=None):
        self._replica = replica
        self._path = path
        # the fleet trace id is addressable for cancellation even before
        # the response echoes one back (hedging cancels losers mid-POST)
        self.trace_id = fleet_trace_id
        self._fleet_trace_id = fleet_trace_id
        self.error = None
        self.ttft = None
        self.tokens = None
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(doc, timeout),
            name="mxnet-fleet-http", daemon=True)
        self._thread.start()

    def _run(self, doc, timeout):
        try:
            body = json.dumps(doc).encode()
            headers = {"Content-Type": "application/json"}
            if self._fleet_trace_id:
                headers["X-MXNet-Trace"] = self._fleet_trace_id
            req = urllib.request.Request(
                self._replica.base_url + self._path, data=body,
                headers=headers)
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                out = json.loads(resp.read())
            self.tokens = out["tokens"]
            self.trace_id = out.get("trace_id") or self._fleet_trace_id
            self.ttft = out.get("ttft_s")
        except urllib.error.HTTPError as e:
            self.error = _error_from_http(e)
        except Exception as e:  # noqa: BLE001 — transport errors surface typed
            self.error = e
        finally:
            self._done.set()

    def wait(self, timeout):
        return self._done.wait(timeout)

    def done(self):
        return self._done.is_set()

    def result(self, timeout):
        if not self._done.wait(timeout):
            raise MXNetError("request timed out after %ss (replica %s)"
                             % (timeout, self._replica.name))
        if self.error is not None:
            raise self.error
        return self.tokens

    def cancel(self):
        return self._replica.cancel(self.trace_id)


def _error_from_http(e):
    """Map an HTTP error from a replica back onto the typed serve
    errors so the router's retry classification is transport-agnostic."""
    try:
        detail = json.loads(e.read()).get("error", "")
    except Exception:  # noqa: BLE001 — diagnostics only
        detail = ""
    msg = "%s (HTTP %d)" % (detail or e.reason, e.code)
    if e.code == 504:
        return ServeDeadlineExceeded(msg)
    if e.code == 404 and "session" in detail:
        return ServeSessionUnknown(msg)
    if e.code == 409:
        return ServeSessionBusy(msg) if "session" in detail \
            else ServeCancelled(msg)
    if e.code == 503:
        err = ServeDraining(msg) if "draining" in detail \
            else ServeQueueFull(msg)
        try:
            err.retry_after_s = clamp_retry_after(
                float(e.headers.get("Retry-After", 1)))
        except (TypeError, ValueError):
            pass
        return err
    return MXNetError(msg)


class HttpReplica:
    """A remote ``LlamaServer`` HTTP front behind the replica surface."""

    def __init__(self, base_url, name=None, probe_timeout=2.0):
        self.base_url = base_url.rstrip("/")
        self.name = name or self.base_url.split("//", 1)[-1]
        self._probe_timeout = probe_timeout

    def probe(self):
        try:
            with urllib.request.urlopen(self.base_url + "/healthz",
                                        timeout=self._probe_timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            # 503 still carries the healthz body (ok=False / draining)
            return json.loads(e.read())

    def metrics(self):
        """Scrape the replica's full registry snapshot
        (``GET /metrics.json``) — a remote replica is its own process,
        so the whole registry is honestly per-replica."""
        with urllib.request.urlopen(self.base_url + "/metrics.json",
                                    timeout=self._probe_timeout) as r:
            return json.loads(r.read())

    def trace(self, trace_id):
        """The replica's stored per-request trace; None when unknown."""
        try:
            with urllib.request.urlopen(
                    self.base_url + "/v1/trace/" + trace_id,
                    timeout=self._probe_timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError:
            return None

    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_s=None, session=None, trace_id=None):
        doc = {"prompt": prompt, "max_new_tokens": max_new_tokens,
               "eos_id": eos_id, "deadline_s": deadline_s}
        if session is not None:
            doc["session"] = session
            return _HttpHandle(self, doc, timeout=300, path="/v1/chat",
                               fleet_trace_id=trace_id)
        return _HttpHandle(self, doc, timeout=300, fleet_trace_id=trace_id)

    def cancel(self, trace_id):
        if trace_id is None:
            return False  # response never arrived: nothing addressable
        # a urllib.request.Request, not a serve future  # mxlint: disable=RL1203
        req = urllib.request.Request(
            self.base_url + "/v1/generate/" + trace_id, method="DELETE")
        try:
            with urllib.request.urlopen(req, timeout=self._probe_timeout):
                return True
        except (urllib.error.URLError, OSError):
            return False

    def reload(self, bundle_path, timeout=60):
        raise MXNetError(
            "HTTP replica %s exposes no reload surface — deploy it from "
            "its own process (mxnet_tpu.serve --bundle ... or mxfleet)"
            % self.name)


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

class _ReplicaState:
    """Router-side view of one replica (guarded by the router lock)."""

    __slots__ = ("ok", "draining", "deploying", "ejected", "failures",
                 "queue_depth", "tpot", "inflight", "not_before_route",
                 "half_open_at", "bundle_sha", "last_error", "probes",
                 "arena_util", "metrics_snap", "metrics_t")

    def __init__(self):
        self.ok = True            # optimistic until the first probe
        self.draining = False
        self.deploying = False
        self.ejected = False
        self.failures = 0         # consecutive bad probes/transports
        self.queue_depth = 0
        self.tpot = 0.0
        self.inflight = 0         # router-side, reacts faster than probes
        self.not_before_route = 0.0   # Retry-After gate
        self.half_open_at = 0.0       # breaker re-probe time
        self.bundle_sha = None
        self.last_error = None
        self.probes = 0
        self.arena_util = 0.0
        self.metrics_snap = None  # last /metrics scrape (lower cadence)
        self.metrics_t = 0.0


class _FleetFuture:
    """``FleetRouter.submit``'s return.

    The first route+submit happens EAGERLY on the submitter's thread —
    sub-millisecond, and the request is in a replica's queue before
    ``submit()`` returns, so decoding starts with no thread hop (a
    per-request waiter thread measured as an 11% throughput tax at
    N=1).  The retry/hedge state machine runs lazily inside
    ``result()`` on the waiter's thread, resolved exactly once."""

    def __init__(self, router, kwargs):
        self._router = router
        self._kwargs = kwargs
        self.tokens = None
        self.error = None
        self.replica = None
        self.ttft = None
        self.trace_id = kwargs.get("trace_id")
        self._lock = threading.Lock()
        self._resolved = False
        self._res = _rescheck.acquire("future", "fleet-req",
                                      scope=router.res_scope)
        deadline_s = kwargs.get("deadline_s")
        self._t0 = router._clock()
        self._deadline_t = None if deadline_s is None \
            else self._t0 + deadline_s
        self._first = router._eager_submit(kwargs, self._deadline_t)

    def done(self):
        if self._resolved:
            return True
        first = self._first
        return (first is not None and first[1] is not None
                and first[1].done())

    def result(self, timeout=300):
        with self._lock:
            if not self._resolved:
                kw = dict(self._kwargs)
                kw["timeout"] = min(timeout, kw.get("timeout", timeout))
                first, self._first = self._first, None
                try:
                    self.tokens = self._router._generate(
                        self, _first=first, _deadline_t=self._deadline_t,
                        _t0=self._t0, **kw)
                except MXNetError as e:
                    self.error = e
                except Exception as e:  # noqa: BLE001 — must resolve typed
                    self.error = MXNetError(
                        "fleet request failed: %s: %s"
                        % (type(e).__name__, e))
                finally:
                    self._resolved = True
                    _rescheck.release(self._res)
                    self._res = None
        if self.error is not None:
            raise self.error
        return self.tokens


class FleetRouter:
    """Routes requests over N replicas; see the module docstring."""

    def __init__(self, replicas, probe_interval=None, retries=None,
                 backoff_s=None, hedge=None, hedge_delay_s=None,
                 eject_after=None, readmit_after_s=None, seed=0,
                 clock=time.monotonic, sleep=time.sleep):
        self._replicas = [self._wrap(r, i) for i, r in enumerate(replicas)]
        if not self._replicas:
            raise MXNetError("FleetRouter needs at least one replica")
        names = [r.name for r in self._replicas]
        if len(set(names)) != len(names):
            raise MXNetError("duplicate replica names: %r" % (names,))
        self._states = {r.name: _ReplicaState() for r in self._replicas}
        self.probe_interval = probe_interval if probe_interval is not None \
            else _env_float("MXNET_FLEET_PROBE_INTERVAL", 0.5)
        self.retries = retries if retries is not None \
            else _env_int("MXNET_FLEET_RETRIES", 2)
        self.backoff_s = backoff_s if backoff_s is not None \
            else _env_float("MXNET_FLEET_BACKOFF", 0.05)
        self.hedge = hedge if hedge is not None \
            else env_flag("MXNET_FLEET_HEDGE", False)
        self.hedge_delay_s = hedge_delay_s if hedge_delay_s is not None \
            else _env_float("MXNET_FLEET_HEDGE_DELAY", 0.0)
        self.eject_after = eject_after if eject_after is not None \
            else _env_int("MXNET_FLEET_EJECT_AFTER", 3)
        self.readmit_after_s = readmit_after_s if readmit_after_s is not None \
            else _env_float("MXNET_FLEET_READMIT_AFTER", 2.0)
        self._clock = clock
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._lock = _lockcheck.named_lock("fleet.router")
        self._lat = collections.deque(maxlen=512)  # ok latencies (hedge p99)
        # session -> replica-name affinity (bounded LRU): a pinned chat
        # session's pages live on ONE replica, so routing its next turn
        # anywhere else is a guaranteed ServeSessionUnknown
        self._affinity = collections.OrderedDict()
        self._affinity_cap = _env_int("MXNET_FLEET_AFFINITY_CAP", 4096)
        self._stop = threading.Event()
        self._poll_thread = None
        self._res_thread = None
        self._http = None
        self.res_scope = "fleet:%x" % id(self)
        # fleet-wide counters (mirrored into telemetry per event)
        self.completed = 0
        self.failed = 0
        self.retried = 0
        self.hedged = 0
        self.ejections = 0
        self.dropped = 0   # requests failed by a drain sweep (shutdown)
        # observability plane (ISSUE 20): trace store, metrics-scrape
        # cadence, optional SLO engine + shed state
        self.metrics_every = max(1, _env_int("MXNET_FLEET_METRICS_EVERY",
                                             4))
        self._trace_ids = itertools.count()
        self._trace_cap = _env_int("MXNET_FLEET_TRACE_CAP", 512)
        self._rtraces = collections.OrderedDict()
        self._slo = None
        self._shedding = False
        self._hedge_saved = None

    @staticmethod
    def _wrap(replica, index):
        if isinstance(replica, (LocalReplica, HttpReplica)):
            return replica
        if isinstance(replica, str):
            return HttpReplica(replica, name="r%d" % index)
        return LocalReplica(replica, name="r%d" % index)

    # -- lifecycle --------------------------------------------------------
    def start(self, poller=True):
        """Probe every replica once (routing needs state before the
        first request), then start the background prober — unless the
        caller drives ``probe_all()`` itself (the chaos matrix does,
        for determinism)."""
        spec = os.environ.get("MXNET_FLEET_SLO")
        if spec and self._slo is None:
            objectives = _slo.parse_objectives(spec)
            if objectives:
                self.attach_slo(_slo.SLOEngine(objectives=objectives,
                                               clock=self._clock))
        self.probe_all()
        if poller and self.probe_interval > 0 and self._poll_thread is None:
            self._stop.clear()
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="mxnet-fleet-probe",
                daemon=True)
            self._poll_thread.start()
            self._res_thread = _rescheck.acquire(
                "thread", "mxnet-fleet-probe", scope=self.res_scope)
        return self

    def _poll_loop(self):
        while not self._stop.wait(self.probe_interval):
            self.probe_all()

    def stop(self):
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)
            self._poll_thread = None
            _rescheck.release(self._res_thread)
            self._res_thread = None
        if self._http is not None:
            self._http.shutdown()
            self._http = None
        if _rescheck.enabled():
            _rescheck.assert_quiescent(scope=self.res_scope)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- probing + circuit breaker ---------------------------------------
    def probe_all(self, metrics=False):
        """One probe sweep.  ``metrics=True`` forces the lower-cadence
        metrics scrape on every replica this sweep (tests and the fleet
        ``/metrics`` endpoint's first serve use it)."""
        for r in self._replicas:
            self._probe_one(r, force_metrics=metrics)
        self._update_healthy_gauge()
        self._slo_tick()

    def _probe_one(self, replica, force_metrics=False):
        now = self._clock()
        with self._lock:
            st = self._states[replica.name]
            if st.ejected and now < st.half_open_at:
                return  # breaker open: not yet time for the half-open probe
        try:
            _faults.maybe_inject("fleet_probe", replica=replica.name)
            doc = replica.probe()
        except Exception as e:  # noqa: BLE001 — a probe must never raise
            with self._lock:
                st.probes += 1
                st.failures += 1
                st.ok = False
                st.last_error = "%s: %s" % (type(e).__name__, e)
            self._maybe_eject(replica, "probe_failure")
            return
        with self._lock:
            st.probes += 1
            st.queue_depth = int(doc.get("queue_depth", 0))
            st.tpot = float(doc.get("tpot_p50_s") or 0.0)
            st.arena_util = float(doc.get("arena_utilization") or 0.0)
            st.draining = bool(doc.get("draining", False))
            st.bundle_sha = doc.get("bundle_sha")
            ok = bool(doc.get("ok", False))
            st.ok = ok
            if ok:
                st.failures = 0
                st.last_error = None
                readmitted = st.ejected
                st.ejected = False
            elif st.draining:
                # draining is deliberate (deploy/shutdown), not a fault:
                # steer away, don't trip the breaker
                readmitted = False
            else:
                st.failures += 1
                st.last_error = doc.get("last_loop_error")
                readmitted = False
            draining = st.draining
            # metrics ride the healthz prober at 1/Nth cadence: a scrape
            # is heavier than a probe (full registry vs one doc), and
            # gauges staler than a few probe intervals still aggregate
            scrape = ok and (force_metrics or st.metrics_snap is None
                             or st.probes % self.metrics_every == 0)
        if readmitted:
            _flight.record("fleet.readmit", replica=replica.name)
        if scrape:
            self._scrape_metrics(replica)
        if ok and not draining:
            return
        self._maybe_eject(replica, "unhealthy")

    def _scrape_metrics(self, replica):
        try:
            snap = replica.metrics()
        except Exception as e:  # noqa: BLE001 — scrape must never kill probe
            _flight.record("fleet.scrape_error", replica=replica.name,
                           error="%s: %s" % (type(e).__name__, e))
            return
        with self._lock:
            st = self._states[replica.name]
            st.metrics_snap = snap
            st.metrics_t = self._clock()

    def _slo_tick(self):
        if self._slo is None:
            return
        try:
            self._slo.observe(self.fleet_metrics_snapshot(),
                              now=self._clock())
        except Exception as e:  # noqa: BLE001 — the prober must survive
            _flight.record("slo.error",
                           error="%s: %s" % (type(e).__name__, e))

    def _maybe_eject(self, replica, reason):
        with self._lock:
            st = self._states[replica.name]
            if st.ejected or st.failures < self.eject_after:
                if st.ejected:  # half-open probe failed: re-arm the timer
                    st.half_open_at = self._clock() + self.readmit_after_s
                return
            st.ejected = True
            st.half_open_at = self._clock() + self.readmit_after_s
            self.ejections += 1
            failures = st.failures
        if _metrics.enabled():
            _metrics.counter(
                "mxnet_fleet_ejections_total",
                help="replicas ejected by the fleet circuit breaker",
                replica=replica.name, reason=reason).inc()
        _flight.record("fleet.eject", replica=replica.name, reason=reason,
                       failures=failures)
        self._update_healthy_gauge()

    def _update_healthy_gauge(self):
        if not _metrics.enabled():
            return
        with self._lock:
            n = sum(1 for st in self._states.values()
                    if not st.ejected and st.ok and not st.draining
                    and not st.deploying)
        _metrics.gauge(
            "mxnet_fleet_replicas_healthy",
            help="replicas currently routable (not ejected/draining)"
        ).set(n)

    # -- fleet metric aggregation -----------------------------------------
    def fleet_metrics_snapshot(self):
        """The fleet-wide merged snapshot: per-replica scrapes merged
        with aggregate semantics (counters sum, gauges per-replica,
        histograms bucket-wise), overlaid with the router's own
        registry for families no scrape carries (``mxnet_fleet_*``,
        ``mxnet_slo_*``, and — in-process — the shared histograms)."""
        missing = []
        with self._lock:
            snaps = {}
            now = self._clock()
            for r in self._replicas:
                st = self._states[r.name]
                if st.metrics_snap is not None:
                    snaps[r.name] = st.metrics_snap
                elif self._routable(st, now):
                    missing.append(r)
        for r in missing:   # first serve before any prober pass
            self._scrape_metrics(r)
        if missing:
            with self._lock:
                for r in missing:
                    snap = self._states[r.name].metrics_snap
                    if snap is not None:
                        snaps[r.name] = snap
        merged = _aggregate.merge_snapshots(snaps)
        return _aggregate.overlay(merged, _metrics.snapshot())

    # -- SLO engine --------------------------------------------------------
    def attach_slo(self, engine, shed=None):
        """Evaluate ``engine`` over the aggregated stream on every probe
        sweep.  ``shed`` (default ``MXNET_FLEET_SLO_SHED``) turns on the
        shed hook: hedging — optional work — is disabled while any
        objective's fast window burns, restored when the alert clears.
        Returns the engine."""
        if shed is None:
            shed = env_flag("MXNET_FLEET_SLO_SHED", False)
        self._slo = engine
        if shed:
            prev_burn, prev_clear = engine._on_burn, engine._on_clear

            def on_burn(name):
                self._shed(True, name)
                if prev_burn is not None:
                    prev_burn(name)

            def on_clear(name):
                self._shed(False, name)
                if prev_clear is not None:
                    prev_clear(name)

            engine._on_burn, engine._on_clear = on_burn, on_clear
        return engine

    def _shed(self, burning, slo_name):
        with self._lock:
            if burning and not self._shedding:
                self._shedding = True
                self._hedge_saved = self.hedge
                self.hedge = False
            elif not burning and self._shedding \
                    and not self._slo.burning():
                self._shedding = False
                self.hedge = self._hedge_saved
            else:
                return
            hedge = self.hedge
        _flight.record("fleet.shed", slo=slo_name,
                       shedding=bool(burning), hedge=bool(hedge))

    # -- distributed tracing ----------------------------------------------
    def _mint_trace(self):
        """Mint a fleet trace id and open its routing-breakdown record.
        The id flows to replicas (in-process / ``X-MXNet-Trace``) so
        ONE id correlates router spans and replica scheduler events."""
        tid = "f%x-%x" % (os.getpid(), next(self._trace_ids))
        with self._lock:
            self._rtraces[tid] = {
                "trace_id": tid, "t0": self._clock(), "status": "submitted",
                "replica": None, "queue_at_router_s": None,
                "total_s": None, "attempts": [], "hedge": None,
            }
            while len(self._rtraces) > self._trace_cap:
                self._rtraces.popitem(last=False)
        _flight.record("fleet.submit", tid=tid)
        return tid

    def _rtrace(self, tid):
        return self._rtraces.get(tid) if tid else None

    def _trace_attempt(self, tid, replica, attempt, role, outcome, t_att):
        """One settled attempt — retry, hedge, or winner — as an
        attributed span: a ``fleet.attempt`` flight event carrying
        ``dur_s`` (rendered as a chrome span on the replica's row) and
        a row in the routing breakdown."""
        now = self._clock()
        dur = max(0.0, now - t_att)
        _flight.record("fleet.attempt", tid=tid or "", replica=replica,
                       attempt=attempt, role=role, outcome=outcome,
                       dur_s=round(dur, 6))
        with self._lock:
            tr = self._rtrace(tid)
            if tr is not None:
                tr["attempts"].append(
                    {"t": round(t_att - tr["t0"], 6), "replica": replica,
                     "attempt": attempt, "role": role, "outcome": outcome,
                     "dur_s": round(dur, 6)})

    def _trace_routed(self, tid):
        """First successful hand-off to a replica: the queue-at-router
        segment of the breakdown ends here."""
        with self._lock:
            tr = self._rtrace(tid)
            if tr is not None and tr["queue_at_router_s"] is None:
                tr["queue_at_router_s"] = round(
                    self._clock() - tr["t0"], 6)
                tr["status"] = "routed"

    def _finish_trace(self, tid, status, winner=None):
        """Terminal state of the fleet-side request: stamps the
        breakdown and records the router-row ``fleet.request`` span."""
        total = None
        with self._lock:
            tr = self._rtrace(tid)
            if tr is not None:
                total = round(self._clock() - tr["t0"], 6)
                tr["status"] = status
                tr["replica"] = winner
                tr["total_s"] = total
        if total is not None:
            _flight.record("fleet.request", tid=tid, status=status,
                           winner=winner or "", dur_s=total)

    def trace(self, trace_id):
        """Fleet-level ``GET /v1/trace/<id>``: the routing breakdown
        (queue-at-router, every attempt, hedge fire time) prepended to
        the owning replica's stored trace.  None when unknown."""
        with self._lock:
            tr = self._rtrace(trace_id)
            if tr is None:
                return None
            tr = dict(tr)
            tr["attempts"] = [dict(a) for a in tr["attempts"]]
            if tr["hedge"] is not None:
                tr["hedge"] = dict(tr["hedge"])
        owner = tr.get("replica")
        if owner is None and tr["attempts"]:
            owner = tr["attempts"][-1]["replica"]
        doc = {"trace_id": trace_id, "fleet": tr, "replica": owner,
               "replica_trace": None}
        rep = next((r for r in self._replicas if r.name == owner), None)
        if rep is not None:
            try:
                doc["replica_trace"] = rep.trace(trace_id)
            except Exception:  # noqa: BLE001 — breakdown still useful alone
                pass
        return doc

    # -- routing ----------------------------------------------------------
    def _routable(self, st, now):
        return (not st.ejected and not st.deploying and not st.draining
                and st.ok and now >= st.not_before_route)

    def _score(self, st):
        # queue depth x TPOT pace = estimated wait; router-side in-flight
        # reacts between probes.  Unknown pace scores by depth alone.
        return (st.queue_depth + st.inflight) * max(st.tpot, 1e-3)

    def _pick(self, exclude=(), prefer=None):
        now = self._clock()
        with self._lock:
            cands = [r for r in self._replicas
                     if r.name not in exclude
                     and self._routable(self._states[r.name], now)]
            # session affinity: the pinning replica wins over p2c
            # whenever it is routable at all (its cached pages beat a
            # shorter queue elsewhere); ejected/draining falls through
            preferred = None
            if prefer is not None:
                preferred = next((r for r in cands if r.name == prefer),
                                 None)
            if not cands:
                gates = [st.not_before_route - now
                         for st in self._states.values()
                         if not st.ejected and st.not_before_route > now]
                err = FleetNoHealthyReplica(
                    "no routable replica (%d total, %d ejected)"
                    % (len(self._replicas),
                       sum(1 for st in self._states.values()
                           if st.ejected)))
                err.retry_after_s = clamp_retry_after(
                    min(gates) if gates else 1.0)
                raise err
            if preferred is not None:
                chosen = preferred
            elif len(cands) == 1:
                chosen = cands[0]
            else:
                a, b = self._rng.sample(cands, 2)
                sa = self._score(self._states[a.name])
                sb = self._score(self._states[b.name])
                chosen = a if sa <= sb else b
            st = self._states[chosen.name]
            st.inflight += 1
            depth = st.queue_depth + st.inflight - 1
        if _metrics.enabled():
            _metrics.histogram(
                "mxnet_fleet_route_queue_depth",
                help="queue depth of the chosen replica at routing time",
                buckets=_ROUTE_DEPTH_BUCKETS).observe(depth)
        return chosen

    def _release(self, replica):
        with self._lock:
            self._states[replica.name].inflight -= 1

    def _note_transport_failure(self, replica, detail):
        """A forward-path transport failure is probe-grade evidence: it
        counts toward the breaker so a dead replica is ejected without
        waiting out the probe interval."""
        st = self._states[replica.name]
        with self._lock:
            st.failures += 1
            st.ok = False
            st.last_error = detail
        self._maybe_eject(replica, "forward_failure")

    def _gate(self, replica, retry_after_s):
        st = self._states[replica.name]
        with self._lock:
            st.not_before_route = max(
                st.not_before_route,
                self._clock() + clamp_retry_after(retry_after_s))

    # -- session affinity --------------------------------------------------
    def _affinity_hint(self, session):
        if session is None:
            return None
        with self._lock:
            name = self._affinity.get(session)
            if name is not None:
                self._affinity.move_to_end(session)
            return name

    def _affinity_note(self, session, name):
        if session is None:
            return
        with self._lock:
            self._affinity[session] = name
            self._affinity.move_to_end(session)
            while len(self._affinity) > self._affinity_cap:
                self._affinity.popitem(last=False)

    def pin_session(self, session, replica_name):
        """Register where a chat session lives — the caller opened it on
        that replica (``LlamaServer.open_session``), so its turns should
        route there.  Later successful turns refresh the pin."""
        if replica_name not in self._states:
            raise MXNetError("unknown replica %r (have %r)"
                             % (replica_name, sorted(self._states)))
        self._affinity_note(session, replica_name)

    # -- request path -----------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_s=None, timeout=300, idempotent=True,
               session=None):
        """Enqueue; routes and submits to a replica before returning, so
        decode starts immediately.  Returns a future whose
        ``.result(timeout)`` drives the retry/hedge state machine.
        ``session`` is a chat-session affinity hint: the turn routes to
        the replica that pinned the session's pages when that replica is
        routable, falling back to p2c otherwise.  The future carries the
        fleet trace id (``.trace_id``) for ``GET /v1/trace/<id>``."""
        return _FleetFuture(self, dict(
            prompt=prompt, max_new_tokens=max_new_tokens, eos_id=eos_id,
            deadline_s=deadline_s, timeout=timeout, idempotent=idempotent,
            session=session, trace_id=self._mint_trace()))

    def _eager_submit(self, kwargs, deadline_t):
        """Attempt 0 on the submitter's thread: route and enqueue now so
        the request reaches a replica queue with no thread hop.  Errors
        are deferred into ``_generate`` (via ``_first``) where the
        normal gate/eject/retry accounting classifies them.  Returns
        ``(replica, handle, error)`` or None to start from scratch."""
        remaining = None
        if deadline_t is not None:
            remaining = deadline_t - self._clock()
            if remaining <= 0:
                return None  # the loop raises ServeDeadlineExceeded
        try:
            replica = self._pick(
                prefer=self._affinity_hint(kwargs.get("session")))
        except FleetNoHealthyReplica as e:
            return (None, None, e)
        try:
            _faults.maybe_inject("fleet_forward", replica=replica.name,
                                 attempt=0)
            handle = replica.submit(
                kwargs["prompt"],
                max_new_tokens=kwargs.get("max_new_tokens"),
                eos_id=kwargs.get("eos_id"), deadline_s=remaining,
                session=kwargs.get("session"),
                trace_id=kwargs.get("trace_id"))
            self._trace_routed(kwargs.get("trace_id"))
            return (replica, handle, None)
        except Exception as e:  # noqa: BLE001 — classified in _generate
            return (replica, None, e)

    def generate(self, prompt, max_new_tokens=None, eos_id=None,
                 deadline_s=None, timeout=300, idempotent=True,
                 session=None):
        """Blocking request through the full route/retry/hedge path."""
        return self._generate(None, prompt, max_new_tokens=max_new_tokens,
                              eos_id=eos_id, deadline_s=deadline_s,
                              timeout=timeout, idempotent=idempotent,
                              session=session,
                              trace_id=self._mint_trace())

    @staticmethod
    def _retry_reason(err):
        """Why a retry is allowed, or None for terminal errors."""
        if isinstance(err, ServeQueueFull):
            return "queue_full"
        if isinstance(err, ServeDraining):
            return "draining"
        if isinstance(err, ServeShutdown):
            return "shutdown"
        if isinstance(err, ServeInternalError):
            return "replica_failed"
        if isinstance(err, (ServeDeadlineExceeded, ServeCancelled)):
            return None
        if isinstance(err, _faults.FaultInjected):
            return "injected"
        if isinstance(err, (ConnectionError, TimeoutError, OSError)):
            return "connection"
        return None

    def _backoff(self, attempt):
        base = min(self.backoff_s * (2 ** attempt), _BACKOFF_CAP_S)
        with self._lock:
            jitter = 0.75 + 0.5 * self._rng.random()
        return base * jitter

    def _generate(self, future, prompt, max_new_tokens=None, eos_id=None,
                  deadline_s=None, timeout=300, idempotent=True,
                  session=None, trace_id=None, _first=None,
                  _deadline_t=None, _t0=None):
        if trace_id is None:
            trace_id = self._mint_trace()
        if _deadline_t is not None:
            deadline_t = _deadline_t
        else:
            deadline_t = None if deadline_s is None \
                else self._clock() + deadline_s
        t0 = self._clock() if _t0 is None else _t0
        tried = set()
        last_err = None
        for attempt in range(self.retries + 1):
            first, _first = (_first, None) if attempt == 0 else (None, None)
            remaining = None
            if deadline_t is not None:
                remaining = deadline_t - self._clock()
                if remaining <= 0:
                    if first is not None and first[0] is not None:
                        self._release(first[0])
                    with self._lock:
                        self.failed += 1
                    self._finish_trace(trace_id, "deadline")
                    raise last_err if isinstance(
                        last_err, ServeDeadlineExceeded) else \
                        ServeDeadlineExceeded(
                            "deadline_s=%.3f expired after %d attempt(s)"
                            % (deadline_s, attempt))
            if first is not None and first[0] is None:
                # eager routing found no healthy replica at submit time
                e = first[2]
                last_err = e
                if attempt >= self.retries:
                    with self._lock:
                        self.failed += 1
                    self._finish_trace(trace_id, "no_replica")
                    raise e
                self._count_retry("no_replica", None, attempt, trace_id)
                self._sleep(self._backoff(attempt))
                tried = set()
                continue
            if first is None:
                try:
                    replica = self._pick(
                        exclude=tried,
                        prefer=self._affinity_hint(session))
                except FleetNoHealthyReplica as e:
                    last_err = e
                    if attempt >= self.retries:
                        with self._lock:
                            self.failed += 1
                        self._finish_trace(trace_id, "no_replica")
                        raise
                    self._count_retry("no_replica", None, attempt,
                                      trace_id)
                    self._sleep(self._backoff(attempt))
                    # a fully-gated fleet may recover: forget per-attempt
                    # exclusions so a re-admitted replica is pickable
                    tried = set()
                    continue
            else:
                replica = first[0]
            tried.add(replica.name)
            # the eager attempt's span starts at submit() time (t0);
            # a retry attempt starts here
            t_att = t0 if first is not None else self._clock()
            try:
                if first is not None:
                    handle = first[1]
                    if first[2] is not None:
                        raise first[2]  # deferred eager-submit error
                else:
                    _faults.maybe_inject("fleet_forward",
                                         replica=replica.name,
                                         attempt=attempt)
                    handle = replica.submit(prompt,
                                            max_new_tokens=max_new_tokens,
                                            eos_id=eos_id,
                                            deadline_s=remaining,
                                            session=session,
                                            trace_id=trace_id)
                    self._trace_routed(trace_id)
                tokens, winner = self._await(handle, replica, tried,
                                             remaining, timeout,
                                             dict(prompt=prompt,
                                                  max_new_tokens=max_new_tokens,
                                                  eos_id=eos_id,
                                                  session=session,
                                                  trace_id=trace_id,
                                                  attempt=attempt))
            except (MXNetError, _faults.FaultInjected) as e:
                self._release(replica)
                reason = self._retry_reason(e)
                self._trace_attempt(trace_id, replica.name, attempt,
                                    "primary", reason or type(e).__name__,
                                    t_att)
                retry_after = getattr(e, "retry_after_s", None)
                if retry_after is not None:
                    self._gate(replica, retry_after)
                self._count_request(replica.name, reason or "error")
                # non-idempotent requests only retry refusals that
                # provably happened before any execution (submit-time)
                if reason is None or attempt >= self.retries or \
                        not (idempotent or isinstance(
                            e, (ServeQueueFull, ServeDraining,
                                _faults.FaultInjected))):
                    with self._lock:
                        self.failed += 1
                        if isinstance(e, ServeShutdown):
                            self.dropped += 1
                    last_err = e
                    self._finish_trace(trace_id, type(e).__name__)
                    raise
                last_err = e
                self._count_retry(reason, replica.name, attempt, trace_id)
                self._sleep(self._backoff(attempt))
                continue
            except (ConnectionError, TimeoutError, OSError) as e:
                self._release(replica)
                self._trace_attempt(trace_id, replica.name, attempt,
                                    "primary", "connection", t_att)
                self._note_transport_failure(
                    replica, "%s: %s" % (type(e).__name__, e))
                self._count_request(replica.name, "connection")
                # a broken transport after submit is ambiguous (the
                # request may have executed): never replay non-idempotent
                if attempt >= self.retries or not idempotent:
                    with self._lock:
                        self.failed += 1
                    self._finish_trace(trace_id, "unreachable")
                    raise MXNetError(
                        "replica %s unreachable after %d attempt(s): %s"
                        % (replica.name, attempt + 1, e))
                last_err = e
                self._count_retry("connection", replica.name, attempt,
                                  trace_id)
                self._sleep(self._backoff(attempt))
                continue
            self._release(replica)
            self._trace_attempt(trace_id, replica.name, attempt, "primary",
                                "ok" if winner.name == replica.name
                                else "lost_to_hedge", t_att)
            self._finish_trace(trace_id, "ok", winner.name)
            self._count_request(winner.name, "ok")
            self._affinity_note(session, winner.name)
            with self._lock:
                self.completed += 1
                self._lat.append(self._clock() - t0)
            if future is not None:
                future.replica = winner.name
                future.ttft = getattr(handle, "ttft", None)
            return tokens
        raise last_err  # pragma: no cover — loop always raises or returns

    def _await(self, handle, replica, tried, remaining, timeout, spec):
        """Wait for ``handle``; with hedging on, fire a second attempt
        on another replica after the p99-derived delay and return the
        first winner (cancelling the loser).  Returns (tokens, winner
        replica).  Every hedge-path transition is an attributed flight
        event — ``fleet.hedge`` when the duplicate fires,
        ``fleet.cancel`` when a loser is cancelled, ``fleet.attempt``
        (role=hedge) when the duplicate settles — all carrying the
        fleet trace id and attempt index."""
        budget = timeout if remaining is None else min(timeout, remaining)
        tid = spec.get("trace_id") or ""
        attempt = spec.get("attempt", 0)
        if not self.hedge or spec.get("session") is not None:
            # a session turn can only run where its pages are pinned —
            # hedging it to another replica is a guaranteed 404
            return handle.result(budget), replica
        delay = self._hedge_delay()
        if handle.wait(delay):
            return handle.result(budget), replica
        try:
            other = self._pick(exclude=tried | {replica.name})
        except FleetNoHealthyReplica:
            self._count_hedge("no_replica")
            return handle.result(budget), replica
        with self._lock:
            self.hedged += 1
        _flight.record("fleet.hedge", tid=tid, attempt=attempt,
                       primary=replica.name, hedge=other.name,
                       delay_s=round(delay, 6))
        with self._lock:
            tr = self._rtrace(tid)
            if tr is not None:
                tr["hedge"] = {"t": round(self._clock() - tr["t0"], 6),
                               "primary": replica.name,
                               "hedge": other.name,
                               "delay_s": round(delay, 6)}
        t_h2 = self._clock()
        h2 = other.submit(spec["prompt"],
                          max_new_tokens=spec["max_new_tokens"],
                          eos_id=spec["eos_id"], deadline_s=remaining,
                          trace_id=spec.get("trace_id"))

        def _hedge_settled(outcome):
            self._trace_attempt(tid, other.name, attempt, "hedge",
                                outcome, t_h2)

        def _cancel_loser(lh, lr):
            lh.cancel()
            _flight.record("fleet.cancel", tid=tid, attempt=attempt,
                           replica=lr.name,
                           role="hedge" if lh is h2 else "primary")
            if lh is h2:
                _hedge_settled("cancelled")

        try:
            pairs = [(handle, replica, "primary_won"),
                     (h2, other, "hedge_won")]
            deadline = self._clock() + budget
            errors = []
            while pairs:
                for i, (h, r, outcome) in enumerate(pairs):
                    if not h.done():
                        continue
                    if h.error is None:
                        if h is h2:
                            _hedge_settled("ok")
                        for lh, lr, _ in pairs[:i] + pairs[i + 1:]:
                            _cancel_loser(lh, lr)
                        self._count_hedge(outcome)
                        return h.result(0.001), r
                    errors.append(h.error)
                    if h is h2:
                        _hedge_settled(type(h.error).__name__)
                    pairs.pop(i)
                    break
                else:
                    if self._clock() >= deadline:
                        for lh, lr, _ in pairs:
                            _cancel_loser(lh, lr)
                        self._count_hedge("timeout")
                        raise errors[0] if errors else MXNetError(
                            "hedged request timed out after %ss" % budget)
                    pairs[0][0].wait(0.002)
            self._count_hedge("both_failed")
            raise errors[-1]
        finally:
            self._release(other)

    def _hedge_delay(self):
        if self.hedge_delay_s > 0:
            return self.hedge_delay_s
        with self._lock:
            data = sorted(self._lat)
        if len(data) >= 16:
            return data[int(0.99 * (len(data) - 1))]
        return 0.05  # cold fleet: a floor beats hedging instantly

    # -- telemetry helpers ------------------------------------------------
    @staticmethod
    def _count_request(replica, status):
        if _metrics.enabled():
            _metrics.counter(
                "mxnet_fleet_requests_total",
                help="fleet requests by replica and final status",
                replica=replica, status=status).inc()

    def _count_retry(self, reason, replica, attempt, trace_id=None):
        with self._lock:
            self.retried += 1
        if _metrics.enabled():
            _metrics.counter(
                "mxnet_fleet_retries_total",
                help="fleet request retries by reason", reason=reason).inc()
        _flight.record("fleet.retry", tid=trace_id or "", reason=reason,
                       replica=replica or "", attempt=attempt)

    @staticmethod
    def _count_hedge(outcome):
        if _metrics.enabled():
            _metrics.counter(
                "mxnet_fleet_hedges_total",
                help="hedged attempts by outcome", outcome=outcome).inc()

    # -- fleet lifecycle --------------------------------------------------
    def rolling_deploy(self, bundle_path, timeout=120):
        """Deploy ``bundle_path`` one replica at a time with zero dropped
        requests: steer traffic away, hot-swap at a step boundary (PR
        15 ``reload()``), re-probe, re-admit.  Raises unless the fleet
        converged to one ``bundle_sha``.  Returns a report dict."""
        _flight.record("fleet.deploy", bundle=str(bundle_path),
                       phase="start", replicas=len(self._replicas))
        report = {"bundle": str(bundle_path), "replicas": [],
                  "dropped_before": self.dropped}
        for replica in self._replicas:
            st = self._states[replica.name]
            with self._lock:
                st.deploying = True
            self._update_healthy_gauge()
            try:
                replica.reload(bundle_path, timeout=timeout)
                self._probe_one(replica)
            finally:
                with self._lock:
                    st.deploying = False
            self._update_healthy_gauge()
            report["replicas"].append(
                {"replica": replica.name, "bundle_sha": st.bundle_sha,
                 "ok": st.ok})
            _flight.record("fleet.deploy", bundle=str(bundle_path),
                           phase="replica", replica=replica.name)
        shas = {r["bundle_sha"] for r in report["replicas"]}
        report["converged"] = len(shas) == 1
        report["bundle_sha"] = next(iter(shas)) if report["converged"] \
            else None
        report["dropped"] = self.dropped - report["dropped_before"]
        _flight.record("fleet.deploy", bundle=str(bundle_path),
                       phase="done", converged=report["converged"])
        if not report["converged"]:
            raise MXNetError(
                "rolling deploy did not converge: bundle_sha per replica "
                "%r" % ([(r["replica"], r["bundle_sha"])
                         for r in report["replicas"]],))
        return report

    def healthz(self):
        """The fleet-level GET /healthz body."""
        now = self._clock()
        with self._lock:
            replicas = {
                name: {"ok": st.ok, "ejected": st.ejected,
                       "draining": st.draining, "deploying": st.deploying,
                       "queue_depth": st.queue_depth,
                       "inflight": st.inflight,
                       "failures": st.failures,
                       "tpot_p50_s": st.tpot,
                       "arena_utilization": st.arena_util,
                       "bundle_sha": st.bundle_sha,
                       "last_error": st.last_error, "probes": st.probes}
                for name, st in self._states.items()}
            healthy = sum(1 for st in self._states.values()
                          if self._routable(st, now))
            shedding = self._shedding
        body = {
            "ok": healthy > 0,
            "replicas_healthy": healthy,
            "replicas_total": len(self._replicas),
            "completed": self.completed, "failed": self.failed,
            "retried": self.retried, "hedged": self.hedged,
            "ejections": self.ejections, "dropped": self.dropped,
            "replicas": replicas,
        }
        if self._slo is not None:
            body["slo"] = {"burning": sorted(
                name for name, b in self._slo._burning.items() if b),
                "shedding": shedding}
        return body

    def stats(self):
        return self.healthz()

    # -- HTTP front -------------------------------------------------------
    def serve_http(self, port=0, host="127.0.0.1"):
        """The fleet's own stdlib HTTP front: POST /v1/generate routes
        through the retry/hedge path; GET /healthz is the fleet view
        (503 + Retry-After when nothing is routable); GET /metrics
        (and /metrics.json) serves the AGGREGATED fleet snapshot —
        per-replica scrapes merged with a ``replica`` label, router
        families overlaid; GET /v1/trace/<id> is the fleet trace —
        routing breakdown prepended to the owning replica's trace."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        router = self

        def _code(err):
            if isinstance(err, ServeDeadlineExceeded):
                return 504
            if isinstance(err, ServeCancelled):
                return 409
            if isinstance(err, (FleetNoHealthyReplica, ServeShutdown,
                                ServeInternalError, ServeDraining,
                                ServeQueueFull)):
                return 503
            return 500

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet: telemetry is the record
                pass

            def _send(self, code, body, ctype="application/json",
                      headers=None):
                payload = body.encode() if isinstance(body, str) \
                    else json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, _metrics.render_text(
                        router.fleet_metrics_snapshot()),
                        ctype="text/plain; version=0.0.4")
                elif self.path == "/metrics.json":
                    self._send(200, router.fleet_metrics_snapshot())
                elif self.path == "/healthz":
                    body = router.healthz()
                    if body["ok"]:
                        self._send(200, body)
                    else:
                        self._send(503, body,
                                   headers={"Retry-After": "1"})
                elif self.path.startswith("/v1/trace/"):
                    tid = self.path[len("/v1/trace/"):]
                    tr = router.trace(tid)
                    if tr is None:
                        self._send(404, {"error": "unknown trace id %r "
                                                  "(evicted or never seen)"
                                                  % tid})
                    else:
                        self._send(200, tr)
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/v1/generate":
                    self._send(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    doc = json.loads(self.rfile.read(n) or b"{}")
                    fut = router.submit(
                        doc["prompt"],
                        max_new_tokens=doc.get("max_new_tokens"),
                        eos_id=doc.get("eos_id"),
                        deadline_s=doc.get("deadline_s"),
                        timeout=doc.get("timeout", 300),
                        idempotent=doc.get("idempotent", True))
                    tokens = fut.result(timeout=doc.get("timeout", 300))
                except (KeyError, ValueError) as e:
                    self._send(400, {"error": str(e)})
                    return
                except MXNetError as e:
                    headers = None
                    retry_after = getattr(e, "retry_after_s", None)
                    if retry_after is not None:
                        headers = {"Retry-After":
                                   str(max(1, int(round(retry_after))))}
                    self._send(_code(e), {"error": str(e)},
                               headers=headers)
                    return
                self._send(200, {"tokens": tokens,
                                 "replica": fut.replica,
                                 "ttft_s": fut.ttft,
                                 "trace_id": fut.trace_id})

        self._http = ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._http.serve_forever,
                         name="mxnet-fleet-http", daemon=True).start()
        return self._http.server_address


def fleet_drive_workload(router, workload, timeout=600,
                         clock=time.monotonic, sleep=time.sleep):
    """Replay a ``poisson_workload`` against a started router — the
    fleet twin of ``drive_workload``.  Returns ``(futures, wall_s)``."""
    t0 = clock()
    futs = []
    for arrival, req in workload:
        lag = arrival - (clock() - t0)
        if lag > 0:
            sleep(lag)
        futs.append(router.submit(req.prompt,
                                  max_new_tokens=req.max_new_tokens,
                                  eos_id=req.eos_id, timeout=timeout))
    for fut in futs:
        try:
            fut.result(timeout=timeout)
        except MXNetError:
            pass  # failures surface via fut.error
    return futs, clock() - t0
