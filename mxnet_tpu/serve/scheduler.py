"""Continuous-batching scheduler: the deterministic serving core.

One ``step()`` is the whole policy — admit, prefill, decode, complete:

1. **admit**: while a decode slot is free, the admission queue is
   non-empty, and the arena can page the head request, pop it, allocate
   its pages, pick the smallest prefill bucket covering the prompt, and
   run prefill — the first generated token falls out of the prefill
   logits, which is when TTFT stops ticking;
2. **decode**: one batched step over every active slot (inactive slots
   ride along pointing at the arena's null page) — or, when speculation
   is on (``spec_k > 0``), one batched **verify** step: each lane
   proposes ``spec_k`` n-gram drafts from its own history
   (serve.spec.propose_ngram) and the compiled ``verify`` signature
   scores all ``spec_k + 1`` positions in one call; the lane accepts
   the longest draft prefix the sampler reproduces exactly, plus one
   bonus token from the first disagreeing position.  Because logits at
   position j only see context <= j, the accepted stream is
   token-for-token what sequential decode would have produced — EOS and
   budget truncation apply mid-block, after acceptance;
3. **complete**: slots whose newest token hit EOS or the budget free
   their pages, fulfill their futures, and are immediately reusable —
   the next ``step()`` refills them from the queue (slot recycling).

The class is jax-free: model execution hides behind a small runner
(``prefill``/``decode``, plus ``verify`` when speculating), so the
scheduler tests drive ``step()`` with a scripted fake and no sleeps,
while the server plugs in the AOT runner and a background thread.
Backpressure is a bounded admission queue — ``submit`` raises
:class:`ServeQueueFull` instead of buffering without limit (HTTP
surfaces it as 503).

Request lifecycle robustness (ISSUE 15): every request can carry a
relative ``deadline_s`` (default ``MXNET_SERVE_DEFAULT_DEADLINE``),
enforced at admission, in-queue and mid-decode — an expired request
fails with :class:`ServeDeadlineExceeded` and frees its pages at the
next step boundary.  ``Request.cancel()`` (or ``DELETE
/v1/generate/<id>``) recycles the lane the same way with
:class:`ServeCancelled`.  ``drain()`` stops admission
(:class:`ServeDraining`, HTTP 503 + ``Retry-After`` estimated from
queue depth and the TPOT EMA) and ``fail_all()`` is the typed-failure
sweep the server's drain timeout, ``stop()`` and loop-crash containment
all use — no future is ever left unresolved.
"""
from __future__ import annotations

import collections
import itertools
import math
import os
import threading
import time

import numpy as np

from ..base import MXNetError
from ..telemetry import flight as _flight
from ..telemetry import metrics as _metrics
from ..testing import faults as _faults
from ..testing import lockcheck as _lockcheck
from ..testing import rescheck as _rescheck
from . import spec as _spec
from .prefix import PrefixCache

# TTFT/TPOT bucket ladders (seconds): decode steps sit well under the
# engine's default op buckets, so the serve histograms get their own
_TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
_TPOT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 1.0)
# accepted-drafts-per-verify ladder (tokens; spec_k is capped at 64)
_ACCEPT_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)

# hybrid-policy match gate: only lanes whose proposer found a real
# n-gram match earn acceptance, so a batch is only *eligible* for the
# verify path when at least this fraction of active lanes matched —
# below it a plain decode emits more tokens per second regardless of
# call costs
_SPEC_MATCH_MIN_FRAC = 0.625
# cost-aware gate on top of the match gate: verify costs more than a
# decode (how much more depends on backend, geometry, and compiled
# width), and pays only when the measured acceptance covers that
# premium.  The scheduler tracks EMAs of both call durations and of
# accepted drafts per lane, fires verify when
#   (1 + acc) * t_decode >= t_verify
# and otherwise decodes plainly — re-probing an apparently-losing
# verify path every N eligible steps so the estimates track workload
# drift.  Zero-duration test clocks make the check degenerate to the
# pure match-gate policy (0 >= 0), so deterministic tests are
# unaffected.
_SPEC_PROBE_EVERY = 32
_SPEC_EMA = 0.2
# verify's fixed overhead is real even at K=1 (~1.4x a decode on CPU,
# ROADMAP item 2).  When the EMAs are warm, the measured acceptance has
# settled below the compiled draft width, and the cost gate has rejected
# the verify path for this many consecutive (probe) verifies, the
# scheduler goes DORMANT: it stops running the per-lane n-gram proposers
# entirely instead of padding too-short drafts to the compiled width, so
# a converged non-speculative phase pays zero speculation overhead per
# step.  Re-probes still fire every _SPEC_PROBE_EVERY steps and one
# winning probe wakes the path back up, so workload drift is tracked
# exactly as before — dormancy can only ever cost probe overhead.
_SPEC_DORMANT_AFTER = 3

# Retry-After hints are clamped to [floor, ceiling]: a cold TPOT EMA can
# emit a ~0s hint (an immediate-stampede invitation to every backed-off
# client at once) and a deep queue x pathological EMA can emit minutes
# (clients give up on a backlog that clears in seconds).  0.05 s is one
# router backoff step; 30 s is the longest a drain/deploy should gate a
# replica (MXNET_SERVE_DRAIN_TIMEOUT's magnitude).
_RETRY_AFTER_FLOOR_S = 0.05
_RETRY_AFTER_CEIL_S = 30.0


def clamp_retry_after(x):
    """Clamp a Retry-After hint (seconds) to the sane band — applied to
    every hint the serve tier emits and every hint the fleet honors."""
    return max(_RETRY_AFTER_FLOOR_S, min(float(x), _RETRY_AFTER_CEIL_S))


class ServeQueueFull(MXNetError):
    """Admission queue at MXNET_SERVE_QUEUE_DEPTH — shed load upstream.
    Carries ``retry_after_s`` (queue-depth x TPOT estimate)."""

    retry_after_s = 1.0


class ServeDraining(MXNetError):
    """Submit refused: the server is draining for shutdown or swap.
    Carries ``retry_after_s`` — HTTP surfaces it as 503 + Retry-After."""

    retry_after_s = 1.0


class ServeDeadlineExceeded(MXNetError):
    """The request's ``deadline_s`` elapsed before completion; its pages
    were freed at the step boundary that noticed."""


class ServeCancelled(MXNetError):
    """The request was cancelled (``Request.cancel()`` or ``DELETE
    /v1/generate/<id>``); the lane recycled at the next step boundary."""


class ServeShutdown(MXNetError):
    """The server stopped or the drain timeout expired while this
    request was still queued or in flight."""


class ServeInternalError(MXNetError):
    """The serve loop hit an unexpected step exception; the affected
    requests are failed with this (naming the cause) instead of hanging
    their futures while the loop restarts."""


class ServeSessionUnknown(MXNetError):
    """The request names a session this server doesn't hold (never
    opened, expired by TTL, or flushed by a drain/swap) — HTTP 404;
    the client reopens with a full-history prompt."""


class ServeSessionBusy(MXNetError):
    """A turn for this session is already queued or in flight —
    sessions are strictly serial (their pinned pages are written by one
    turn at a time); HTTP 409."""


class Request:
    """One generation request and its (thread-safe) result future."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens=None, eos_id=None,
                 deadline_s=None, session_id=None, trace_id=None):
        self.rid = next(Request._ids)
        # globally-unique-enough id stamped into flight events and served
        # back by GET /v1/trace/<id> (pid disambiguates across ranks).
        # A caller-supplied id (the FleetRouter's fleet trace id, carried
        # in-process or via X-MXNet-Trace) overrides the self-minted one
        # so router and replica spans correlate on ONE id.
        self.trace_id = str(trace_id) if trace_id \
            else "%x-%x" % (os.getpid(), self.rid)
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise MXNetError("empty prompt")
        self.max_new_tokens = int(max_new_tokens
                                  if max_new_tokens is not None
                                  else _env_int("MXNET_SERVE_MAX_NEW", 128))
        if self.max_new_tokens <= 0:
            raise MXNetError("max_new_tokens must be positive")
        self.eos_id = None if eos_id is None else int(eos_id)
        # relative wall-clock budget (submit -> finish); the env default
        # applies to requests that don't set one, 0/unset = no deadline
        if deadline_s is None:
            deadline_s = _env_float("MXNET_SERVE_DEFAULT_DEADLINE", 0.0)
        self.deadline_s = float(deadline_s) if deadline_s else None
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise MXNetError("deadline_s must be positive")
        self.deadline_t = None    # absolute (scheduler clock), at submit
        # chat-session turn (ISSUE 19): the prompt is the DELTA — only
        # the new turn's tokens — and prefill resumes over the session's
        # pinned pages.  None = ordinary stateless request.
        self.session_id = None if session_id is None else str(session_id)
        self.cache_hit_tokens = 0  # prompt tokens spliced from the cache
        self._cancel = False
        self.tokens = []          # generated ids (never includes prompt)
        self.submit_t = None      # clock() at admission-queue entry
        self.admit_t = None       # clock() when a decode slot was assigned
        self.first_token_t = None  # clock() when prefill produced token 0
        self.first_decode_t = None  # clock() at the first decode-step token
        self.finish_t = None
        self.error = None
        self._done = threading.Event()
        self._res = None          # rescheck token, set at queue entry

    @property
    def ttft(self):
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    def breakdown(self):
        """Where the TTFT went: queue wait, prefill, first decode step.
        Unreached phases are None (e.g. a rejected request has only
        ``queue_wait=None``)."""
        def _d(a, b):
            return None if a is None or b is None else b - a
        return {
            "queue_wait_s": _d(self.submit_t, self.admit_t),
            "prefill_s": _d(self.admit_t, self.first_token_t),
            "first_decode_s": _d(self.first_token_t, self.first_decode_t),
            "ttft_s": self.ttft,
            "cache_hit_tokens": self.cache_hit_tokens,
        }

    def result(self, timeout=None):
        """Block for the generated tokens (raises the request's error)."""
        if not self._done.wait(timeout):
            raise MXNetError("request %d still in flight after %ss"
                             % (self.rid, timeout))
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def cancel(self):
        """Request cancellation from any thread.  The scheduler notices
        at the next step boundary: the lane recycles, pages free, and
        ``result()`` raises :class:`ServeCancelled`.  No-op once done."""
        self._cancel = True

    @property
    def cancelled(self):
        return self._cancel

    def done(self):
        return self._done.is_set()


class _Slot:
    """One in-flight decode lane: request + position + block-table row."""

    __slots__ = ("req", "pages", "row", "position", "proposer",
                 "base", "pf_rem", "pf_pos")

    def __init__(self, req, pages, row, position, base=0, pf_rem=None,
                 pf_pos=0):
        self.req = req
        self.pages = pages
        self.row = row            # np (maxp,) int32 block-table row
        self.position = position  # next token's position (0-based)
        self.proposer = None      # lazy spec.NgramProposer (spec_k > 0)
        # ISSUE 19: history length already in the arena before this
        # request's prompt (session turns; 0 for stateless requests) —
        # every position in the lane is offset by it
        self.base = base
        # chunked-prefill state: tokens still to write (None/[] = the
        # lane is decoding) and the next absolute write position.  A
        # cache splice starts pf_pos past the hit; a session turn past
        # the pinned history's written coverage.
        self.pf_rem = pf_rem
        self.pf_pos = pf_pos


class _Session:
    """One pinned chat conversation: committed token stream + the arena
    pages holding its KV between turns (owner tag ``sess:<id>``).

    ``written`` is the KV *coverage* — positions ``[0, written)`` are
    correct in the arena.  It trails ``len(tokens)`` by at least one:
    the final sampled token of a turn is never fed back as decode input,
    so its KV was never written; the next turn's chunked prefill rewrites
    the stream from ``written`` (purity makes the rewrite exact).
    """

    __slots__ = ("sid", "owner", "tokens", "written", "pages", "busy",
                 "deadline_t", "res")

    def __init__(self, sid, deadline_t):
        self.sid = sid
        self.owner = "sess:%s" % sid
        self.tokens = []          # full committed history, all turns
        self.written = 0          # arena KV coverage (tokens, not pages)
        self.pages = []           # pinned pages covering `written`
        self.busy = None          # rid of the queued/active turn
        self.deadline_t = deadline_t
        self.res = None           # rescheck token while pinned


def _env_int(name, default):
    v = os.environ.get(name, "")
    return int(v) if v.strip() else default


def _env_float(name, default):
    v = os.environ.get(name, "")
    return float(v) if v.strip() else default


def greedy_sampler(logits, req):
    """Default sampler: argmax on host (deterministic, no device work)."""
    return int(np.argmax(logits))


class Scheduler:
    """Admission + in-flight batching over a runner and a page arena.

    ``runner`` needs two methods (numpy in, numpy out):
    ``prefill(bucket, tokens (Lp,), length, block_row) -> logits (V,)``
    and ``decode(tokens (B,), positions (B,), block_tables (B, maxp))
    -> logits (B, V)`` — plus ``verify(tokens (B, K+1), positions,
    block_tables) -> logits (B, K+1, V)`` when the scheduler runs with
    ``spec_k > 0``.  ``clock`` is injectable so tests measure nothing
    real.

    ``spec_k`` is the *runtime* draft count: defaults to the bundle's
    compiled ``geometry.spec_k``, may be lowered (drafts are padded up
    to the compiled verify width, extra positions never accepted), and
    ``spec_k=0`` turns speculation off entirely (plain decode path) —
    the parity knob the e2e matrix flips.
    """

    def __init__(self, runner, arena, queue_depth=None, sampler=None,
                 clock=time.monotonic, spec_k=None):
        self.runner = runner
        self.arena = arena
        self.geometry = arena.geometry
        self.queue_depth = int(queue_depth if queue_depth is not None
                               else _env_int("MXNET_SERVE_QUEUE_DEPTH", 64))
        self.sampler = sampler or greedy_sampler
        self.clock = clock
        spec_k = self.geometry.spec_k if spec_k is None else int(spec_k)
        if not 0 <= spec_k <= self.geometry.spec_k:
            raise MXNetError(
                "runtime spec_k=%d out of range for this bundle "
                "(compiled verify width spec_k=%d; 0 disables "
                "speculation)" % (spec_k, self.geometry.spec_k))
        self.spec_k = spec_k
        # verify scatters the full compiled draft width past the lane's
        # position even when the runtime spec_k is lower, so pages must
        # cover that many extra slots beyond prompt + budget
        self._spec_headroom = self.geometry.spec_k if spec_k > 0 else 0
        self._lock = _lockcheck.named_lock("serve.sched")
        # MXNET_RESCHECK: futures tracked from queue entry to resolution
        # are scoped per scheduler so one server's quiescence check
        # ignores another's live requests
        self.res_scope = "sched:%x" % id(self)
        self._queue = collections.deque()
        self._slots = [None] * self.geometry.max_batch
        self._work = _lockcheck.named_condition("serve.sched", self._lock)
        self._draining = False      # drain(): no new admissions, ever
        self._hold_admission = False  # hot-swap: queue keeps, slots wait
        self._refuse_error = None   # loop gave up: fail submits fast
        # aggregate counters (served through stats()/telemetry)
        self.admitted = 0
        self.rejected = 0
        self.completed = 0
        self.tokens_generated = 0
        self.decode_steps = 0
        self.prefills = 0
        self.chunk_steps = 0      # batched chunked-prefill calls
        # ISSUE 19: chunked prefill + prefix cache + sessions.  All
        # three need the mid-sequence `chunk` executable, so a bundle
        # exported with prefill_chunk=0 serves exactly as before; with
        # it compiled, MXNET_SERVE_PREFIX_CACHE (default on) gates the
        # radix cache and POST /v1/chat sessions come alive.
        self.chunk_size = int(self.geometry.prefill_chunk)
        cache_on = os.environ.get("MXNET_SERVE_PREFIX_CACHE",
                                  "1").strip() not in ("0", "false", "")
        self.prefix_cache = PrefixCache(arena) \
            if self.chunk_size > 0 and cache_on else None
        self.session_ttl = _env_float("MXNET_SERVE_SESSION_TTL", 600.0)
        self._sessions = {}       # sid -> _Session (under _lock)
        self._session_seq = itertools.count()
        self.spec_proposed = 0    # draft tokens sent to verify
        self.spec_accepted = 0    # draft tokens the sampler reproduced
        # cost-model EMAs for the verify/decode policy (see the
        # _SPEC_PROBE_EVERY comment).  Acceptance starts at the compiled
        # width — optimism makes the first eligible steps verify, which
        # is what seeds the duration estimates with real measurements.
        self._t_decode = 0.0
        self._t_verify = 0.0
        self._spec_acc_lane = float(self.spec_k)
        self._spec_skipped = 0    # eligible steps since the last verify
        self._spec_lose_streak = 0  # consecutive gate-rejected verifies
        self._spec_dormant = False  # proposers parked until a probe wins
        self._ttfts = collections.deque(maxlen=4096)
        self._tpots = collections.deque(maxlen=4096)
        # per-request traces (GET /v1/trace/<id>): bounded FIFO so a
        # long-lived server can't grow without limit.  Own lock — trace
        # events are appended while self._lock is held (non-reentrant).
        self._trace_lock = _lockcheck.named_lock("serve.trace")
        self._traces = collections.OrderedDict()
        self._trace_cap = _env_int("MXNET_SERVE_TRACE_CAP", 512)

    # -- per-request tracing ----------------------------------------------
    def _trace_new(self, req):
        with self._trace_lock:
            self._traces[req.trace_id] = {
                "trace_id": req.trace_id, "rid": req.rid,
                "prompt_len": len(req.prompt), "status": "queued",
                "events": [],
            }
            while len(self._traces) > self._trace_cap:
                self._traces.popitem(last=False)

    def _trace_event(self, req, event, status=None, **fields):
        """One scheduler transition: stamped into the flight ring (with
        the request's trace id) AND onto the request's stored trace."""
        _flight.record("serve." + event, tid=req.trace_id, rid=req.rid,
                       **fields)
        with self._trace_lock:
            tr = self._traces.get(req.trace_id)
            if tr is None:
                return
            tr["events"].append(dict(fields, event=event, t=self.clock()))
            if status is not None:
                tr["status"] = status

    def trace(self, trace_id):
        """The stored trace of one request (``GET /v1/trace/<id>``);
        None when unknown/evicted."""
        with self._trace_lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                return None
            tr = dict(tr)
            tr["events"] = [dict(e) for e in tr["events"]]
            return tr

    # -- admission --------------------------------------------------------
    def pick_bucket(self, prompt_len):
        """Smallest prefill bucket covering ``prompt_len`` (None: too
        long for the ladder — reject at submit, not at prefill)."""
        for b in self.geometry.prefill_buckets:
            if prompt_len <= b:
                return b
        return None

    def submit(self, req):
        """Queue ``req``; backpressure + obvious rejections happen NOW."""
        self._trace_new(req)
        # over-ladder prompts are only fatal without the chunk
        # executable: with prefill_chunk > 0 any prompt that fits the
        # context prefills in ladder-sized chunks instead
        if self.pick_bucket(len(req.prompt)) is None \
                and self.chunk_size <= 0 and req.session_id is None:
            self._reject(req, MXNetError(
                "prompt of %d tokens exceeds the largest prefill bucket "
                "(%d) this bundle was exported with (export with "
                "prefill_chunk > 0 to serve over-bucket prompts)"
                % (len(req.prompt), self.geometry.prefill_buckets[-1])))
            return req
        total = len(req.prompt) + req.max_new_tokens + self._spec_headroom
        if req.session_id is None and total > self.geometry.max_context:
            self._reject(req, MXNetError(
                "prompt %d + max_new %d%s exceeds max context %d (= "
                "max_pages_per_seq x page_size)"
                % (len(req.prompt), req.max_new_tokens,
                   " + spec_k headroom %d" % self._spec_headroom
                   if self._spec_headroom else "",
                   self.geometry.max_context)))
            return req
        with self._lock:
            if self._refuse_error is not None:
                self.rejected += 1
                self._count_req("rejected")
                self._trace_event(req, "rejected", status="rejected",
                                  reason="loop_dead")
                raise type(self._refuse_error)(str(self._refuse_error))
            if self._draining:
                self.rejected += 1
                self._count_req("rejected")
                self._trace_event(req, "rejected", status="rejected",
                                  reason="draining")
                err = ServeDraining(
                    "server is draining — not accepting new requests "
                    "(retry against another replica, or after ~%ds)"
                    % self._retry_after_locked())
                err.retry_after_s = self._retry_after_locked()
                raise err
            if len(self._queue) >= self.queue_depth:
                self.rejected += 1
                self._count_req("rejected")
                self._trace_event(req, "rejected", status="rejected",
                                  reason="queue_full")
                err = ServeQueueFull(
                    "admission queue full (%d waiting, "
                    "MXNET_SERVE_QUEUE_DEPTH=%d)"
                    % (len(self._queue), self.queue_depth))
                err.retry_after_s = self._retry_after_locked()
                raise err
            if req.session_id is not None:
                sess = self._sessions.get(req.session_id)
                if sess is None:
                    self.rejected += 1
                    self._count_req("rejected")
                    self._trace_event(req, "rejected", status="rejected",
                                      reason="session_unknown")
                    raise ServeSessionUnknown(
                        "unknown session %r (never opened, expired after "
                        "MXNET_SERVE_SESSION_TTL, or flushed by a "
                        "drain/swap) — reopen with the full history"
                        % req.session_id)
                if sess.busy is not None:
                    self.rejected += 1
                    self._count_req("rejected")
                    self._trace_event(req, "rejected", status="rejected",
                                      reason="session_busy")
                    raise ServeSessionBusy(
                        "session %r already has a turn in flight "
                        "(request %d) — sessions are serial"
                        % (req.session_id, sess.busy))
                total = (len(sess.tokens) + len(req.prompt)
                         + req.max_new_tokens + self._spec_headroom)
                if total > self.geometry.max_context:
                    self.rejected += 1
                    self._count_req("rejected")
                    self._trace_event(req, "rejected", status="rejected",
                                      reason="over_context")
                    raise MXNetError(
                        "session %r history %d + turn prompt %d + "
                        "max_new %d exceeds max context %d"
                        % (req.session_id, len(sess.tokens),
                           len(req.prompt), req.max_new_tokens,
                           self.geometry.max_context))
                # serialize the session NOW: a second turn submitted
                # while this one is queued gets ServeSessionBusy, and
                # the TTL reaper skips busy sessions
                sess.busy = req.rid
            req.submit_t = self.clock()
            if req.deadline_s is not None:
                req.deadline_t = req.submit_t + req.deadline_s
            self._queue.append(req)
            req._res = _rescheck.acquire("future", req.trace_id,
                                         scope=self.res_scope)
            self._trace_event(req, "submit", prompt_len=len(req.prompt))
            self._gauges_locked()
            self._work.notify()
        return req

    def _reject(self, req, err):
        self.rejected += 1
        self._count_req("rejected")
        self._trace_event(req, "rejected", status="rejected",
                          reason=str(err))
        req.error = err
        req.finish_t = self.clock()
        req._done.set()

    # -- the scheduling step ---------------------------------------------
    def step(self):
        """One reap→admit→chunk→decode→complete round; True if any
        work ran.  The reap phase is where deadlines, cancellations and
        injected client disconnects take effect — pages free and futures
        resolve at step boundaries, never mid-call.  Chunked prefill
        interleaves with decode at exactly one chunk call per step, so a
        long prompt costs every active lane one extra call per chunk
        instead of one monolithic bucket-sized stall."""
        self._poll_disconnects()
        worked = self._reap()
        if self._admit():
            worked = True
        if self._chunk_once():
            worked = True
        if self._decode_once():
            worked = True
        return worked

    # -- lifecycle: deadlines + cancellation ------------------------------
    def _lifecycle_error(self, req, now):
        """(error, status) if ``req`` should stop now, else (None, None).
        Cancellation wins over expiry — the client asked first."""
        if req._cancel:
            return ServeCancelled(
                "request %s cancelled after %d token(s)"
                % (req.trace_id, len(req.tokens))), "cancelled"
        if req.deadline_t is not None and now > req.deadline_t:
            return ServeDeadlineExceeded(
                "request %s exceeded deadline_s=%.3f with %d token(s) "
                "generated" % (req.trace_id, req.deadline_s,
                               len(req.tokens))), "expired"
        return None, None

    def _poll_disconnects(self):
        """Chaos seam: the ``client_disconnect`` site fires once per
        step per live request, and a raising action becomes a cancel —
        the deterministic stand-in for a vanished client."""
        if _faults.current() is None:
            return
        with self._lock:
            live = list(self._queue) + [s.req for s in self._slots
                                        if s is not None]
        for req in live:
            try:
                _faults.maybe_inject("client_disconnect", rid=req.rid,
                                     tid=req.trace_id)
            except _faults.LoopKilled:
                raise
            except Exception:
                req.cancel()

    def _reap(self):
        """Fail every queued/in-flight request whose deadline passed or
        that was cancelled; frees pages immediately.  True if any died."""
        now = self.clock()
        dead_q, dead_s = [], []
        with self._lock:
            if self._queue:
                keep = collections.deque()
                for req in self._queue:
                    err, status = self._lifecycle_error(req, now)
                    if err is None:
                        keep.append(req)
                    else:
                        dead_q.append((req, err, status))
                if dead_q:
                    self._queue = keep
                    self._gauges_locked()
            for s in self._slots:
                if s is None:
                    continue
                err, status = self._lifecycle_error(s.req, now)
                if err is not None:
                    dead_s.append((s, err, status))
            expired = self._reap_sessions_locked(now)
        for req, err, status in dead_q:
            self._fail_queued(req, err, status)
        for s, err, status in dead_s:
            self._finish_slot(s, error=err, status=status)
        return bool(dead_q or dead_s or expired)

    def _reap_sessions_locked(self, now):
        """TTL eviction over idle sessions (the PR 15 deadline pattern):
        a session whose ``deadline_t`` passed with no turn in flight
        unpins its pages — shared pages decrement, exclusive ones
        recycle.  Busy sessions never expire mid-turn."""
        expired = [s for s in self._sessions.values()
                   if s.busy is None and now > s.deadline_t]
        for sess in expired:
            del self._sessions[sess.sid]
            if sess.pages:
                self.arena.free(sess.pages, owner=sess.owner)
            _rescheck.release(sess.res)
            sess.res = None
            _flight.record("session.expire", sid=sess.sid,
                           tokens=len(sess.tokens), pages=len(sess.pages),
                           reason="ttl")
        if expired:
            self._gauges_locked()
        return len(expired)

    def _fail_queued(self, req, err, status):
        """Resolve a request that never reached a slot (reaped from the
        queue, drained, or shut down) — no pages to free."""
        req.error = err
        req.finish_t = self.clock()
        self._count_req(status)
        self._trace_event(req, "finish", status=status, tokens=0,
                          error=type(err).__name__)
        with self._trace_lock:
            tr = self._traces.get(req.trace_id)
            if tr is not None:
                tr["tokens"] = []
                tr["breakdown"] = req.breakdown()
                tr["error"] = str(err)
        req._done.set()
        _rescheck.release(req._res)
        req._res = None
        if req.session_id is not None:
            with self._lock:
                sess = self._sessions.get(req.session_id)
                if sess is not None and sess.busy == req.rid:
                    sess.busy = None
                    sess.deadline_t = self.clock() + self.session_ttl

    def cancel(self, trace_id):
        """Cancel by trace id (``DELETE /v1/generate/<id>``): True if
        the request is queued or in flight; the lane recycles at the
        next step boundary."""
        with self._lock:
            for req in self._queue:
                if req.trace_id == trace_id:
                    req.cancel()
                    self._work.notify()
                    return True
            for s in self._slots:
                if s is not None and s.req.trace_id == trace_id:
                    s.req.cancel()
                    return True
        return False

    # -- chat sessions (ISSUE 19) -----------------------------------------
    def open_session(self):
        """Create a pinned multi-turn session; returns its id.

        Needs the mid-sequence ``chunk`` executable: a later turn's
        delta prefills from the pinned history's write coverage, which
        a position-0 bucket prefill cannot do.
        """
        if self.chunk_size <= 0:
            raise MXNetError(
                "sessions need a bundle exported with prefill_chunk > 0 "
                "(MXNET_SERVE_PREFILL_CHUNK) — turn deltas prefill "
                "mid-sequence")
        with self._lock:
            sid = "s%x-%x" % (os.getpid(), next(self._session_seq))
            self._sessions[sid] = _Session(
                sid, self.clock() + self.session_ttl)
            self._gauges_locked()
        _flight.record("session.create", sid=sid)
        return sid

    def close_session(self, session_id):
        """Explicitly unpin a session's pages (``DELETE /v1/chat/<id>``).
        True if it existed; raises :class:`ServeSessionBusy` while a
        turn is in flight."""
        with self._lock:
            sess = self._sessions.get(str(session_id))
            if sess is None:
                return False
            if sess.busy is not None:
                raise ServeSessionBusy(
                    "session %r has a turn in flight — cancel it first"
                    % session_id)
            del self._sessions[sess.sid]
            if sess.pages:
                self.arena.free(sess.pages, owner=sess.owner)
            _rescheck.release(sess.res)
            sess.res = None
            self._gauges_locked()
        _flight.record("session.expire", sid=str(session_id),
                       reason="closed")
        return True

    def session_count(self):
        with self._lock:
            return len(self._sessions)

    def release_shared(self):
        """Drop every cross-request reference — the whole prefix cache
        and every session pin.  The flush step of ``fail_all``, drain,
        ``stop()`` and hot-swap: after it (and after in-flight requests
        resolve) the arena owes pages to nobody, so quiescence asserts
        and ``arena.reset()`` hold."""
        with self._lock:
            self._release_shared_locked()

    def _release_shared_locked(self):
        if self.prefix_cache is not None:
            self.prefix_cache.release_all()
        for sess in list(self._sessions.values()):
            if sess.pages:
                self.arena.free(sess.pages, owner=sess.owner)
            _rescheck.release(sess.res)
            sess.res = None
            _flight.record("session.expire", sid=sess.sid,
                           reason="flush")
        self._sessions.clear()
        self._gauges_locked()

    # -- drain / shutdown -------------------------------------------------
    def drain(self):
        """Stop admission permanently: every subsequent submit raises
        :class:`ServeDraining` (HTTP 503 + Retry-After).  Queued and
        in-flight requests keep being served — the server's ``drain()``
        gives them ``MXNET_SERVE_DRAIN_TIMEOUT`` to finish."""
        with self._lock:
            self._draining = True

    @property
    def draining(self):
        return self._draining

    def refuse(self, err):
        """Fail every subsequent submit fast with a copy of ``err`` —
        the give-up state after repeated loop crashes (and the stopped
        state, so a submit racing ``stop()`` cannot queue a future
        nobody resolves).  ``None`` reopens the window."""
        with self._lock:
            self._refuse_error = err

    def hold_admission(self, hold):
        """Pause (True) / resume (False) slot admission while keeping
        the queue intact — the hot-swap window: old lanes drain on the
        old runner, queued requests wait for the new arena, nothing is
        dropped."""
        with self._lock:
            self._hold_admission = bool(hold)

    def swap(self, runner, arena):
        """Atomically repoint the scheduler at a new runner + arena.
        Only legal at a step boundary with zero active slots (the
        server's reload path holds admission and drains lanes first) —
        live block tables must never cross arenas."""
        with self._lock:
            busy = sum(1 for s in self._slots if s is not None)
            if busy:
                raise MXNetError(
                    "runner/arena swap with %d active slot(s) — drain "
                    "lanes first" % busy)
            # cached prefixes and session pins point into the OLD
            # arena — flush them (sessions die across a swap; clients
            # get ServeSessionUnknown and reopen with full history)
            self._release_shared_locked()
            self.runner = runner
            self.arena = arena
            self.geometry = arena.geometry
            self.chunk_size = int(arena.geometry.prefill_chunk)
            if self.prefix_cache is not None:
                self.prefix_cache = PrefixCache(arena)

    def fail_all(self, error, status="failed"):
        """Resolve EVERY queued and in-flight request with ``error``
        (pages freed, futures set); returns how many were failed.  The
        drain timeout, ``stop()`` and loop-crash containment land here
        — the no-hung-futures guarantee."""
        with self._lock:
            queued = list(self._queue)
            self._queue.clear()
            slots = [s for s in self._slots if s is not None]
            self._gauges_locked()
        for req in queued:
            self._fail_queued(req, error, status)
        for slot in slots:
            # _finish_slot skips slots a racing completion already closed
            self._finish_slot(slot, error=error, status=status)
        # with every request resolved (its page refs dropped), flush the
        # cross-request refs too — containment's arena.reset() needs
        # zero owners, and no future is left to revive a session anyway
        self.release_shared()
        return len(queued) + len(slots)

    def kick(self):
        """Wake a parked serve loop (drain/reload want a step now)."""
        with self._work:
            self._work.notify_all()

    def _retry_after_locked(self):
        """Seconds until the backlog plausibly clears: queued requests x
        mean budget x TPOT EMA / batch width, clamped to the
        [0.05 s, 30 s] band (callers hold _lock).  The clamp matters at
        both ends: a cold EMA (est ~0) must not invite an immediate
        stampede, and a deep queue must not emit a minutes-long hint."""
        budgets = [r.max_new_tokens for r in self._queue]
        tpot = self._t_decode
        if tpot <= 0.0 and self._tpots:
            data = sorted(self._tpots)
            tpot = data[len(data) // 2]
        if not budgets or tpot <= 0.0:
            return 1.0
        est = (len(budgets) * (sum(budgets) / len(budgets)) * tpot
               / max(1, self.geometry.max_batch))
        return clamp_retry_after(est)

    def retry_after_s(self):
        """Public Retry-After estimate (see ``_retry_after_locked``)."""
        with self._lock:
            return self._retry_after_locked()

    def _admit(self):
        admitted = False
        while True:
            dead = None
            with self._lock:
                if self._hold_admission:
                    break
                free = [i for i, s in enumerate(self._slots) if s is None]
                if not free or not self._queue:
                    break
                req = self._queue[0]
                sess = None
                if req.session_id is not None:
                    sess = self._sessions.get(req.session_id)
                    if sess is None or sess.busy != req.rid:
                        # the session was flushed (drain/swap raced the
                        # queue) — fail the turn outside the lock
                        self._queue.popleft()
                        dead = req
                if dead is None:
                    slot = self._admit_head_locked(req, sess, free[0])
                    if slot is None:
                        break  # head-of-line waits for pages, not slots
            if dead is not None:
                self._fail_queued(dead, ServeSessionUnknown(
                    "session %r vanished before this turn was admitted "
                    "(flushed by a drain or swap)" % dead.session_id),
                    "failed")
                continue
            if slot.pf_rem is None:
                self._prefill(slot)
            admitted = True
        return admitted

    def _admit_head_locked(self, req, sess, slot_i):
        """Page + splice the queue head into ``slot_i``; None when the
        arena can't page it yet.

        The splice: cached prefix pages (or the session's pinned pages)
        enter the block table by reference — ``retain`` under the
        request's tag — and only the *uncovered* tail allocates fresh
        pages.  Under pressure the prefix cache evicts LRU cache-only
        pages once before the head gives up for this step.
        """
        base = start = hit = 0
        shared, hit_pages = [], []
        if sess is not None:
            base, start, shared = len(sess.tokens), sess.written, sess.pages
        elif self.prefix_cache is not None:
            hit_pages, hit = self.prefix_cache.match(req.prompt)
            try:
                _faults.maybe_inject("serve_splice", rid=req.rid,
                                     pages=len(hit_pages))
            except _faults.LoopKilled:
                raise
            except Exception:
                # chaos seam: a raising splice fault abandons the hit
                # (nothing was retained yet) — the request admits cold
                hit_pages, hit = [], 0
            start = hit
        total = (base + len(req.prompt) + req.max_new_tokens
                 + self._spec_headroom)
        need = self.arena.pages_needed(total)
        fresh_n = need - len(shared) - len(hit_pages)
        fresh = []
        if fresh_n > 0:
            fresh = self.arena.alloc(fresh_n, req.rid)
            if fresh is None:
                if self.prefix_cache is not None and self.prefix_cache.evict(
                        fresh_n - self.arena.free_pages):
                    fresh = self.arena.alloc(fresh_n, req.rid)
                if fresh is None:
                    return None
        if hit_pages:
            self.arena.retain(hit_pages, req.rid)
            self.prefix_cache.record_hit(hit, len(hit_pages))
            req.cache_hit_tokens = hit
        elif sess is None and self.prefix_cache is not None:
            self.prefix_cache.record_miss()
        if shared:
            self.arena.retain(shared, req.rid)
        pages = list(shared) + list(hit_pages) + list(fresh)
        pf_rem = None
        if sess is not None:
            # rewrite the history's unwritten tail (at least the last
            # sampled token of the previous turn) plus this turn's delta
            pf_rem = (sess.tokens + req.prompt)[start:]
        elif hit or self.pick_bucket(len(req.prompt)) is None:
            pf_rem = req.prompt[hit:]
        slot = _Slot(req, pages, self.arena.block_row(pages),
                     position=base + len(req.prompt), base=base,
                     pf_rem=pf_rem, pf_pos=start)
        self._queue.popleft()
        self._slots[slot_i] = slot
        self.admitted += 1
        req.admit_t = self.clock()
        self._count_req("admitted")
        self._trace_event(req, "admit", status="active",
                          slot=slot_i, pages=len(pages), cache_hit=hit,
                          session=req.session_id or "")
        if _metrics.enabled() and req.submit_t is not None:
            _metrics.histogram(
                "mxnet_serve_queue_wait_seconds",
                help="submit -> decode-slot assignment "
                     "(TTFT breakdown: time spent queued)",
                buckets=_TTFT_BUCKETS,
            ).observe(req.admit_t - req.submit_t)
        self._gauges_locked()
        return slot

    def _prefill(self, slot):
        req = slot.req
        bucket = self.pick_bucket(len(req.prompt))
        t0 = self.clock()
        try:
            _faults.maybe_inject("serve_prefill", rid=req.rid,
                                 bucket=bucket)
            logits = self.runner.prefill(
                bucket, np.asarray(req.prompt, dtype=np.int32),
                len(req.prompt), slot.row)
        except _faults.LoopKilled:  # chaos: escapes to loop containment
            self._fail_slot(slot, ServeInternalError(
                "serve loop killed during prefill"))
            raise
        except Exception as e:  # poison the request, free the lane
            self._fail_slot(slot, e)
            return
        self.prefills += 1
        first = self.sampler(logits, req)
        req.tokens.append(first)
        self.tokens_generated += 1
        req.first_token_t = self.clock()
        ttft = req.first_token_t - req.submit_t
        self._ttfts.append(ttft)
        self._trace_event(req, "prefill", bucket=bucket,
                          prefill_s=req.first_token_t - t0, ttft_s=ttft)
        if _metrics.enabled():
            _metrics.histogram(
                "mxnet_serve_ttft_seconds",
                help="submit -> first generated token (prefill included)",
                buckets=_TTFT_BUCKETS).observe(ttft)
            _metrics.histogram(
                "mxnet_serve_prefill_seconds",
                help="wall time of one bucketed prefill call",
                buckets=_TTFT_BUCKETS).observe(req.first_token_t - t0)
        if self.prefix_cache is not None:
            with self._lock:
                self.prefix_cache.insert(req.prompt, slot.pages)
        self._maybe_complete(slot)

    def _chunk_once(self):
        """One batched chunked-prefill call over every lane still
        writing its prompt (or session-history tail).  Runs once per
        step, interleaved with the decode call, so prompt ingestion
        shares the loop fairly with token generation.  Lanes whose last
        chunk lands sample their first token from the chunk's logits —
        same contract as bucket prefill."""
        with self._lock:
            filling = [(i, s) for i, s in enumerate(self._slots)
                       if s is not None and s.pf_rem]
        if not filling:
            return False
        g = self.geometry
        C = self.chunk_size
        tokens = np.zeros((g.max_batch, C), dtype=np.int32)
        positions = np.zeros(g.max_batch, dtype=np.int32)
        tables = np.zeros((g.max_batch, g.max_pages_per_seq),
                          dtype=np.int32)
        take = {}
        for i, s in filling:
            n = min(C, len(s.pf_rem))
            take[i] = n
            # a partial final chunk pads with token 0: the pad rows land
            # at positions past the lane's real stream and every such
            # position is rewritten (and its page's slot-0 scale reset)
            # by a real row before any query can attend it — the same
            # purity argument that makes pages shareable at all
            tokens[i, :n] = s.pf_rem[:n]
            positions[i] = s.pf_pos
            tables[i] = s.row
        t0 = self.clock()
        try:
            _faults.maybe_inject("serve_chunk", batch=len(filling))
            logits = self.runner.chunk(tokens, positions, tables)
        except _faults.LoopKilled:  # chaos: escapes to loop containment
            for _, s in filling:
                self._fail_slot(s, ServeInternalError(
                    "serve loop killed during chunked prefill"))
            raise
        except Exception as e:
            for _, s in filling:
                self._fail_slot(s, e)
            return True
        self.chunk_steps += 1
        dt = self.clock() - t0
        _flight.record("serve.chunk", batch=len(filling),
                       dur=round(dt, 6))
        for i, s in filling:
            n = take[i]
            s.pf_rem = s.pf_rem[n:]
            s.pf_pos += n
            if not s.pf_rem:
                s.pf_rem = None
                self._finish_prefill(s, logits[i, n - 1])
        return True

    def _finish_prefill(self, slot, last_logits):
        """The lane's last prompt token just landed: sample the first
        generated token and close out TTFT — the chunked twin of the
        ``_prefill`` tail."""
        req = slot.req
        self.prefills += 1
        first = self.sampler(last_logits, req)
        req.tokens.append(first)
        self.tokens_generated += 1
        req.first_token_t = self.clock()
        ttft = req.first_token_t - req.submit_t
        self._ttfts.append(ttft)
        prefill_s = req.first_token_t - req.admit_t
        self._trace_event(req, "prefill", chunked=True,
                          cache_hit=req.cache_hit_tokens,
                          prefill_s=prefill_s, ttft_s=ttft)
        if _metrics.enabled():
            _metrics.histogram(
                "mxnet_serve_ttft_seconds",
                help="submit -> first generated token (prefill included)",
                buckets=_TTFT_BUCKETS).observe(ttft)
            _metrics.histogram(
                "mxnet_serve_prefill_seconds",
                help="wall time of one bucketed prefill call",
                buckets=_TTFT_BUCKETS).observe(prefill_s)
        if self.prefix_cache is not None and req.session_id is None:
            with self._lock:
                self.prefix_cache.insert(req.prompt, slot.pages)
        self._maybe_complete(slot)

    def _decode_once(self):
        with self._lock:
            active = [(i, s) for i, s in enumerate(self._slots)
                      if s is not None and not s.pf_rem]
        if not active:
            return False
        if self.spec_k > 0 and self._spec_dormant \
                and self._spec_skipped < _SPEC_PROBE_EVERY:
            # dormant: the path converged below the compiled width and
            # kept losing — skip the per-lane proposers entirely (the
            # verify call's fixed overhead AND the draft padding are
            # gone, not just the acceptance) until the next re-probe
            self._spec_skipped += 1
        elif self.spec_k > 0:
            proposals = {}
            matched = 0
            for i, s in active:
                if s.proposer is None:
                    s.proposer = _spec.NgramProposer(
                        s.req.prompt + s.req.tokens)
                d, n = s.proposer.propose(self.spec_k)
                proposals[i] = d
                matched += 1 if n > 0 else 0
            if matched >= max(1, math.ceil(
                    _SPEC_MATCH_MIN_FRAC * len(active))):
                # until both call types have real duration samples the
                # gate stays open — never conclude verify loses from a
                # cold estimate.  The 1.05 margin demands a strict win:
                # an EV-neutral verify still pays per-call host-side
                # acceptance work, and a borderline estimate would
                # otherwise oscillate with measurement noise.
                pays = (self._t_decode == 0.0 or self._t_verify == 0.0
                        or (1.0 + self._spec_acc_lane) * self._t_decode
                        >= 1.05 * self._t_verify)
                if pays or self._spec_skipped >= _SPEC_PROBE_EVERY:
                    self._spec_skipped = 0
                    return self._verify_once(active, proposals)
                self._spec_skipped += 1
            # hybrid policy: too few lanes have a real n-gram match
            # (unmatched lanes ride a verify call at full cost but
            # accept ~nothing), or the measured acceptance doesn't
            # cover the measured verify premium at this geometry — the
            # batch earns more from a plain decode this step.  Output
            # is identical either way: acceptance is exact (see
            # _verify_once).
        g = self.geometry
        tokens = np.zeros(g.max_batch, dtype=np.int32)
        positions = np.zeros(g.max_batch, dtype=np.int32)
        tables = np.zeros((g.max_batch, g.max_pages_per_seq),
                          dtype=np.int32)
        for i, s in active:
            tokens[i] = s.req.tokens[-1]
            positions[i] = s.position
            tables[i] = s.row
        t0 = self.clock()
        try:
            _faults.maybe_inject("serve_decode", batch=len(active))
            logits = self.runner.decode(tokens, positions, tables)
        except _faults.LoopKilled:  # chaos: escapes to loop containment
            for _, s in active:
                self._fail_slot(s, ServeInternalError(
                    "serve loop killed during decode"))
            raise
        except Exception as e:
            for _, s in active:
                self._fail_slot(s, e)
            return True
        self.decode_steps += 1
        dt = self.clock() - t0
        self._t_decode += _SPEC_EMA * (dt - self._t_decode)
        # one flight event per batched step, not per request — decode is
        # the serve hot loop and the ring must outlast a request's life
        _flight.record("serve.decode", batch=len(active), dur=round(dt, 6))
        for i, s in active:
            s.position += 1
            tok = self.sampler(logits[i], s.req)
            s.req.tokens.append(tok)
            if s.proposer is not None:  # keep the n-gram index in sync
                s.proposer.append(tok)
            self.tokens_generated += 1
            self._tpots.append(dt)
            req = s.req
            if req.first_decode_t is None and len(req.tokens) >= 2:
                req.first_decode_t = self.clock()
                self._trace_event(
                    req, "first_decode",
                    first_decode_s=req.first_decode_t - req.first_token_t)
                if _metrics.enabled() and req.first_token_t is not None:
                    _metrics.histogram(
                        "mxnet_serve_first_decode_seconds",
                        help="first token -> first decode-step token "
                             "(TTFT breakdown: decode pipeline entry)",
                        buckets=_TPOT_BUCKETS,
                    ).observe(req.first_decode_t - req.first_token_t)
            self._maybe_complete(s)
        if _metrics.enabled():
            _metrics.histogram(
                "mxnet_serve_tpot_seconds",
                help="wall time of one batched decode step",
                buckets=_TPOT_BUCKETS).observe(dt)
            _metrics.counter(
                "mxnet_serve_decode_steps_total",
                help="batched decode steps executed").inc()
            _metrics.counter(
                "mxnet_serve_tokens_total",
                help="tokens generated across all requests",
            ).inc(len(active))
        return True

    def _verify_once(self, active, proposals):
        """One speculative round: score each lane's proposed drafts at
        all ``spec_k + 1`` positions in one compiled verify call, accept
        the longest exactly-matching prefix + one bonus token.  Only
        reached when some lane's proposer found a real n-gram match
        (``_decode_once``'s hybrid policy); matchless steps use the
        cheaper plain decode call.

        Exactness: position j's logits only attend context <= j, so
        ``sampler(logits[i, j])`` equals what a plain decode at that
        position would sample.  Draft j+1 is accepted iff it equals that
        sample; the first disagreement's sample is emitted instead
        (never wasted — it is exactly the next sequential token).  EOS /
        budget truncation run over the emitted block in order, so a
        mid-block stop leaves the same tokens a sequential loop would.
        """
        g = self.geometry
        K = g.spec_k              # compiled verify width (>= runtime)
        tokens = np.zeros((g.max_batch, K + 1), dtype=np.int32)
        positions = np.zeros(g.max_batch, dtype=np.int32)
        tables = np.zeros((g.max_batch, g.max_pages_per_seq),
                          dtype=np.int32)
        drafts = {}
        for i, s in active:
            req = s.req
            d = list(proposals[i])
            d += [d[-1]] * (K - len(d))   # pad to the compiled width
            drafts[i] = d
            tokens[i, 0] = req.tokens[-1]
            tokens[i, 1:] = d
            positions[i] = s.position
            tables[i] = s.row
        t0 = self.clock()
        try:
            _faults.maybe_inject("serve_decode", batch=len(active))
            logits = self.runner.verify(tokens, positions, tables)
        except _faults.LoopKilled:  # chaos: escapes to loop containment
            for _, s in active:
                self._fail_slot(s, ServeInternalError(
                    "serve loop killed during verify"))
            raise
        except Exception as e:
            for _, s in active:
                self._fail_slot(s, e)
            return True
        self.decode_steps += 1
        dt = self.clock() - t0
        total_accepted = total_took = 0
        for i, s in active:
            req, d = s.req, drafts[i]
            emitted, j = [], 0
            while True:
                tok = self.sampler(logits[i, j], req)
                emitted.append(tok)
                # padded positions past the runtime spec_k never accept
                if j < self.spec_k and d[j] == tok:
                    j += 1
                    continue
                break
            accepted = len(emitted) - 1
            self.spec_proposed += self.spec_k
            self.spec_accepted += accepted
            total_accepted += accepted
            took = 0
            for tok in emitted:
                if len(req.tokens) >= req.max_new_tokens:
                    break
                req.tokens.append(tok)
                took += 1
                if req.eos_id is not None and tok == req.eos_id:
                    break
            self.tokens_generated += took
            total_took += took
            if took and s.proposer is not None:
                # index only the tokens that landed: EOS/budget-dropped
                # block tails must not pollute future proposals
                s.proposer.extend(req.tokens[-took:])
            # invariant: position = where the NEXT call's input token
            # (req.tokens[-1]) sits in the stream (base = session
            # history already in the arena)
            s.position = s.base + len(req.prompt) + len(req.tokens) - 1
            self._tpots.append(dt / max(1, took))
            if req.first_decode_t is None and len(req.tokens) >= 2:
                req.first_decode_t = self.clock()
                self._trace_event(
                    req, "first_decode",
                    first_decode_s=req.first_decode_t - req.first_token_t)
                if _metrics.enabled() and req.first_token_t is not None:
                    _metrics.histogram(
                        "mxnet_serve_first_decode_seconds",
                        help="first token -> first decode-step token "
                             "(TTFT breakdown: decode pipeline entry)",
                        buckets=_TPOT_BUCKETS,
                    ).observe(req.first_decode_t - req.first_token_t)
            if _metrics.enabled():
                _metrics.histogram(
                    "mxnet_serve_spec_accept_length",
                    help="draft tokens accepted per lane per verify call",
                    buckets=_ACCEPT_BUCKETS).observe(accepted)
            self._maybe_complete(s)
        self._t_verify += _SPEC_EMA * (dt - self._t_verify)
        self._spec_acc_lane += _SPEC_EMA * (
            total_accepted / len(active) - self._spec_acc_lane)
        # dormancy bookkeeping (see _SPEC_DORMANT_AFTER): a verify that
        # the warm cost gate would now reject, with acceptance settled
        # below the compiled width, extends the losing streak; any
        # winning verify resets it and wakes a dormant path immediately
        warm = self._t_decode > 0.0 and self._t_verify > 0.0
        loses = warm and (1.0 + self._spec_acc_lane) * self._t_decode \
            < 1.05 * self._t_verify
        if loses and self._spec_acc_lane < float(self.geometry.spec_k):
            self._spec_lose_streak += 1
            if self._spec_lose_streak >= _SPEC_DORMANT_AFTER:
                self._spec_dormant = True
        else:
            self._spec_lose_streak = 0
            self._spec_dormant = False
        _flight.record("serve.verify", batch=len(active),
                       accepted=total_accepted, dur=round(dt, 6))
        if _metrics.enabled():
            _metrics.histogram(
                "mxnet_serve_tpot_seconds",
                help="wall time of one batched decode step",
                buckets=_TPOT_BUCKETS).observe(dt)
            _metrics.counter(
                "mxnet_serve_decode_steps_total",
                help="batched decode steps executed").inc()
            _metrics.counter(
                "mxnet_serve_spec_proposed_tokens_total",
                help="n-gram draft tokens sent to verify",
            ).inc(self.spec_k * len(active))
            _metrics.counter(
                "mxnet_serve_spec_accepted_tokens_total",
                help="draft tokens accepted by exact-match verification",
            ).inc(total_accepted)
            _metrics.counter(
                "mxnet_serve_tokens_total",
                help="tokens generated across all requests",
            ).inc(total_took)
        return True

    # -- completion -------------------------------------------------------
    def _maybe_complete(self, slot):
        req = slot.req
        # mid-decode lifecycle enforcement: a deadline crossed during
        # the step that just ran (or a cancel that raced it) frees the
        # pages NOW, not at the next reap
        err, status = self._lifecycle_error(req, self.clock())
        if err is not None:
            self._finish_slot(slot, error=err, status=status)
            return
        done = len(req.tokens) >= req.max_new_tokens
        if req.eos_id is not None and req.tokens \
                and req.tokens[-1] == req.eos_id:
            done = True
        if done:
            self._finish_slot(slot, error=None)

    def _fail_slot(self, slot, err):
        self._finish_slot(slot, error=err)

    def _finish_slot(self, slot, error, status=None):
        req = slot.req
        with self._lock:
            live = False
            for i, s in enumerate(self._slots):
                if s is slot:
                    self._slots[i] = None
                    live = True
                    break
            if not live:
                return  # a racing fail_all/complete already closed it
            if req.session_id is not None:
                sess = self._sessions.get(req.session_id)
                if sess is not None and sess.busy == req.rid:
                    if error is None and req.tokens:
                        # commit the turn: tokens join the history, and
                        # the pages covering the written KV get a
                        # session reference before the request's refs
                        # drop.  A FAILED turn commits nothing — its
                        # garbage rows past `written` are rewritten by
                        # the next turn's chunked prefill before any
                        # query can attend them (purity).
                        sess.tokens.extend(req.prompt + req.tokens)
                        sess.written = (slot.base + len(req.prompt)
                                        + len(req.tokens) - 1)
                        keep = self.arena.pages_needed(sess.written)
                        grown = slot.pages[len(sess.pages):keep]
                        if grown:
                            self.arena.retain(grown, sess.owner)
                            sess.pages = sess.pages + list(grown)
                        if sess.res is None:
                            sess.res = _rescheck.acquire(
                                "session", sess.owner,
                                scope=self.arena.res_scope)
                        _flight.record("session.turn", sid=sess.sid,
                                       tid=req.trace_id,
                                       tokens=len(sess.tokens),
                                       pages=len(sess.pages))
                    sess.busy = None
                    sess.deadline_t = self.clock() + self.session_ttl
            self.arena.free(slot.pages, owner=req.rid)
            self.completed += 1
            if status is None:
                status = "failed" if error is not None else "completed"
            self._count_req(status)
            self._gauges_locked()
        req.error = error
        req.finish_t = self.clock()
        self._trace_event(req, "finish", status=status,
                          tokens=len(req.tokens),
                          error=(type(error).__name__ if error else ""))
        with self._trace_lock:
            tr = self._traces.get(req.trace_id)
            if tr is not None:
                tr["tokens"] = list(req.tokens)
                tr["breakdown"] = req.breakdown()
                if error is not None:
                    tr["error"] = str(error)
        req._done.set()
        _rescheck.release(req._res)
        req._res = None

    # -- introspection ----------------------------------------------------
    def active_slots(self):
        with self._lock:
            return sum(1 for s in self._slots if s is not None)

    def queue_len(self):
        with self._lock:
            return len(self._queue)

    def has_work(self):
        with self._lock:
            return bool(self._queue) \
                or any(s is not None for s in self._slots)

    def wait_for_work(self, timeout):
        """Server-thread parking: wake on submit or after ``timeout``."""
        with self._work:
            if not self._queue and all(s is None for s in self._slots):
                self._work.wait(timeout)

    def percentile(self, which, q):
        """Exact percentile over the recent-window deques ('ttft'/'tpot')."""
        data = sorted(self._ttfts if which == "ttft" else self._tpots)
        if not data:
            return 0.0
        i = min(len(data) - 1, int(round(q * (len(data) - 1))))
        return data[i]

    def stats(self):
        with self._lock:
            active = sum(1 for s in self._slots if s is not None)
            qlen = len(self._queue)
            sessions = len(self._sessions)
            prefix = self.prefix_cache.stats() if self.prefix_cache \
                else {"prefix_hits": 0, "prefix_misses": 0,
                      "prefix_hit_rate": 0.0, "prefix_cached_tokens": 0,
                      "prefix_pages": 0, "prefix_evictions": 0}
            shared = self.arena.shared_pages()
        out = {
            "prefix_enabled": self.prefix_cache is not None,
            "prefill_chunk": self.chunk_size,
            "chunk_steps": self.chunk_steps,
            "sessions": sessions,
            "shared_pages": shared,
        }
        out.update(prefix)
        out.update({
            "admitted": self.admitted, "rejected": self.rejected,
            "completed": self.completed,
            "tokens_generated": self.tokens_generated,
            "decode_steps": self.decode_steps, "prefills": self.prefills,
            "active_slots": active, "queue_len": qlen,
            "draining": self._draining,
            "arena_utilization": self.arena.utilization(),
            "ttft_p50_s": self.percentile("ttft", 0.50),
            "ttft_p99_s": self.percentile("ttft", 0.99),
            "tpot_p50_s": self.percentile("tpot", 0.50),
            "spec_k": self.spec_k, "kv_dtype": self.geometry.kv_dtype,
            "spec_proposed_tokens": self.spec_proposed,
            "spec_accepted_tokens": self.spec_accepted,
            "spec_accept_rate": (self.spec_accepted
                                 / float(self.spec_proposed)
                                 if self.spec_proposed else 0.0),
        })
        return out

    def _count_req(self, status):
        if not _metrics.enabled():
            return
        _metrics.counter(
            "mxnet_serve_requests_total",
            help="requests by outcome", status=status).inc()
        # dedicated lifecycle families (ISSUE 15) so dashboards alert on
        # them without label math over requests_total
        if status == "expired":
            _metrics.counter(
                "mxnet_serve_expired_total",
                help="requests failed by deadline expiry").inc()
        elif status == "cancelled":
            _metrics.counter(
                "mxnet_serve_cancelled_total",
                help="requests cancelled before completion").inc()
        elif status == "drained":
            _metrics.counter(
                "mxnet_serve_drained_total",
                help="requests failed by drain timeout or shutdown").inc()

    def _gauges_locked(self):
        if _metrics.enabled():
            _metrics.gauge(
                "mxnet_serve_queue_depth",
                help="requests waiting for admission").set(len(self._queue))
            _metrics.gauge(
                "mxnet_serve_batch_occupancy",
                help="active decode slots (of max_batch)",
            ).set(sum(1 for s in self._slots if s is not None))
            _metrics.gauge(
                "mxnet_serve_sessions_active",
                help="pinned chat sessions holding arena pages between "
                     "turns").set(len(self._sessions))
