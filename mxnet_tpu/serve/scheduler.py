"""Continuous-batching scheduler: the deterministic serving core.

One ``step()`` is the whole policy — admit, prefill, decode, complete:

1. **admit**: while a decode slot is free, the admission queue is
   non-empty, and the arena can page the head request, pop it, allocate
   its pages, pick the smallest prefill bucket covering the prompt, and
   run prefill — the first generated token falls out of the prefill
   logits, which is when TTFT stops ticking;
2. **decode**: one batched step over every active slot (inactive slots
   ride along pointing at the arena's null page);
3. **complete**: slots whose newest token hit EOS or the budget free
   their pages, fulfill their futures, and are immediately reusable —
   the next ``step()`` refills them from the queue (slot recycling).

The class is jax-free: model execution hides behind a two-method runner
(``prefill``/``decode``), so the scheduler tests drive ``step()`` with a
scripted fake and no sleeps, while the server plugs in the AOT runner
and a background thread.  Backpressure is a bounded admission queue —
``submit`` raises :class:`ServeQueueFull` instead of buffering without
limit (HTTP surfaces it as 503).
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time

import numpy as np

from ..base import MXNetError
from ..telemetry import flight as _flight
from ..telemetry import metrics as _metrics

# TTFT/TPOT bucket ladders (seconds): decode steps sit well under the
# engine's default op buckets, so the serve histograms get their own
_TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
_TPOT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 1.0)


class ServeQueueFull(MXNetError):
    """Admission queue at MXNET_SERVE_QUEUE_DEPTH — shed load upstream."""


class Request:
    """One generation request and its (thread-safe) result future."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens=None, eos_id=None):
        self.rid = next(Request._ids)
        # globally-unique-enough id stamped into flight events and served
        # back by GET /v1/trace/<id> (pid disambiguates across ranks)
        self.trace_id = "%x-%x" % (os.getpid(), self.rid)
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise MXNetError("empty prompt")
        self.max_new_tokens = int(max_new_tokens
                                  if max_new_tokens is not None
                                  else _env_int("MXNET_SERVE_MAX_NEW", 128))
        if self.max_new_tokens <= 0:
            raise MXNetError("max_new_tokens must be positive")
        self.eos_id = None if eos_id is None else int(eos_id)
        self.tokens = []          # generated ids (never includes prompt)
        self.submit_t = None      # clock() at admission-queue entry
        self.admit_t = None       # clock() when a decode slot was assigned
        self.first_token_t = None  # clock() when prefill produced token 0
        self.first_decode_t = None  # clock() at the first decode-step token
        self.finish_t = None
        self.error = None
        self._done = threading.Event()

    @property
    def ttft(self):
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    def breakdown(self):
        """Where the TTFT went: queue wait, prefill, first decode step.
        Unreached phases are None (e.g. a rejected request has only
        ``queue_wait=None``)."""
        def _d(a, b):
            return None if a is None or b is None else b - a
        return {
            "queue_wait_s": _d(self.submit_t, self.admit_t),
            "prefill_s": _d(self.admit_t, self.first_token_t),
            "first_decode_s": _d(self.first_token_t, self.first_decode_t),
            "ttft_s": self.ttft,
        }

    def result(self, timeout=None):
        """Block for the generated tokens (raises the request's error)."""
        if not self._done.wait(timeout):
            raise MXNetError("request %d still in flight after %ss"
                             % (self.rid, timeout))
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def done(self):
        return self._done.is_set()


class _Slot:
    """One in-flight decode lane: request + position + block-table row."""

    __slots__ = ("req", "pages", "row", "position")

    def __init__(self, req, pages, row, position):
        self.req = req
        self.pages = pages
        self.row = row            # np (maxp,) int32 block-table row
        self.position = position  # next token's position (0-based)


def _env_int(name, default):
    v = os.environ.get(name, "")
    return int(v) if v.strip() else default


def greedy_sampler(logits, req):
    """Default sampler: argmax on host (deterministic, no device work)."""
    return int(np.argmax(logits))


class Scheduler:
    """Admission + in-flight batching over a runner and a page arena.

    ``runner`` needs two methods (numpy in, numpy out):
    ``prefill(bucket, tokens (Lp,), length, block_row) -> logits (V,)``
    and ``decode(tokens (B,), positions (B,), block_tables (B, maxp))
    -> logits (B, V)``.  ``clock`` is injectable so tests measure
    nothing real.
    """

    def __init__(self, runner, arena, queue_depth=None, sampler=None,
                 clock=time.monotonic):
        self.runner = runner
        self.arena = arena
        self.geometry = arena.geometry
        self.queue_depth = int(queue_depth if queue_depth is not None
                               else _env_int("MXNET_SERVE_QUEUE_DEPTH", 64))
        self.sampler = sampler or greedy_sampler
        self.clock = clock
        self._lock = threading.Lock()
        self._queue = collections.deque()
        self._slots = [None] * self.geometry.max_batch
        self._work = threading.Condition(self._lock)
        # aggregate counters (served through stats()/telemetry)
        self.admitted = 0
        self.rejected = 0
        self.completed = 0
        self.tokens_generated = 0
        self.decode_steps = 0
        self.prefills = 0
        self._ttfts = collections.deque(maxlen=4096)
        self._tpots = collections.deque(maxlen=4096)
        # per-request traces (GET /v1/trace/<id>): bounded FIFO so a
        # long-lived server can't grow without limit.  Own lock — trace
        # events are appended while self._lock is held (non-reentrant).
        self._trace_lock = threading.Lock()
        self._traces = collections.OrderedDict()
        self._trace_cap = _env_int("MXNET_SERVE_TRACE_CAP", 512)

    # -- per-request tracing ----------------------------------------------
    def _trace_new(self, req):
        with self._trace_lock:
            self._traces[req.trace_id] = {
                "trace_id": req.trace_id, "rid": req.rid,
                "prompt_len": len(req.prompt), "status": "queued",
                "events": [],
            }
            while len(self._traces) > self._trace_cap:
                self._traces.popitem(last=False)

    def _trace_event(self, req, event, status=None, **fields):
        """One scheduler transition: stamped into the flight ring (with
        the request's trace id) AND onto the request's stored trace."""
        _flight.record("serve." + event, tid=req.trace_id, rid=req.rid,
                       **fields)
        with self._trace_lock:
            tr = self._traces.get(req.trace_id)
            if tr is None:
                return
            tr["events"].append(dict(fields, event=event, t=self.clock()))
            if status is not None:
                tr["status"] = status

    def trace(self, trace_id):
        """The stored trace of one request (``GET /v1/trace/<id>``);
        None when unknown/evicted."""
        with self._trace_lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                return None
            tr = dict(tr)
            tr["events"] = [dict(e) for e in tr["events"]]
            return tr

    # -- admission --------------------------------------------------------
    def pick_bucket(self, prompt_len):
        """Smallest prefill bucket covering ``prompt_len`` (None: too
        long for the ladder — reject at submit, not at prefill)."""
        for b in self.geometry.prefill_buckets:
            if prompt_len <= b:
                return b
        return None

    def submit(self, req):
        """Queue ``req``; backpressure + obvious rejections happen NOW."""
        self._trace_new(req)
        if self.pick_bucket(len(req.prompt)) is None:
            self._reject(req, MXNetError(
                "prompt of %d tokens exceeds the largest prefill bucket "
                "(%d) this bundle was exported with"
                % (len(req.prompt), self.geometry.prefill_buckets[-1])))
            return req
        total = len(req.prompt) + req.max_new_tokens
        if total > self.geometry.max_context:
            self._reject(req, MXNetError(
                "prompt %d + max_new %d exceeds max context %d (= "
                "max_pages_per_seq x page_size)"
                % (len(req.prompt), req.max_new_tokens,
                   self.geometry.max_context)))
            return req
        with self._lock:
            if len(self._queue) >= self.queue_depth:
                self.rejected += 1
                self._count_req("rejected")
                self._trace_event(req, "rejected", status="rejected",
                                  reason="queue_full")
                raise ServeQueueFull(
                    "admission queue full (%d waiting, "
                    "MXNET_SERVE_QUEUE_DEPTH=%d)"
                    % (len(self._queue), self.queue_depth))
            req.submit_t = self.clock()
            self._queue.append(req)
            self._trace_event(req, "submit", prompt_len=len(req.prompt))
            self._gauges_locked()
            self._work.notify()
        return req

    def _reject(self, req, err):
        self.rejected += 1
        self._count_req("rejected")
        self._trace_event(req, "rejected", status="rejected",
                          reason=str(err))
        req.error = err
        req.finish_t = self.clock()
        req._done.set()

    # -- the scheduling step ---------------------------------------------
    def step(self):
        """One admit→prefill→decode→complete round; True if any work ran."""
        worked = self._admit()
        if self._decode_once():
            worked = True
        return worked

    def _admit(self):
        admitted = False
        while True:
            with self._lock:
                free = [i for i, s in enumerate(self._slots) if s is None]
                if not free or not self._queue:
                    break
                req = self._queue[0]
                pages = self.arena.alloc(
                    self.arena.pages_needed(
                        len(req.prompt) + req.max_new_tokens), req.rid)
                if pages is None:
                    break  # head-of-line waits for pages, not forever slots
                self._queue.popleft()
                slot_i = free[0]
                slot = _Slot(req, pages, self.arena.block_row(pages),
                             position=len(req.prompt))
                self._slots[slot_i] = slot
                self.admitted += 1
                req.admit_t = self.clock()
                self._count_req("admitted")
                self._trace_event(req, "admit", status="active",
                                  slot=slot_i, pages=len(pages))
                if _metrics.enabled() and req.submit_t is not None:
                    _metrics.histogram(
                        "mxnet_serve_queue_wait_seconds",
                        help="submit -> decode-slot assignment "
                             "(TTFT breakdown: time spent queued)",
                        buckets=_TTFT_BUCKETS,
                    ).observe(req.admit_t - req.submit_t)
                self._gauges_locked()
            self._prefill(slot)
            admitted = True
        return admitted

    def _prefill(self, slot):
        req = slot.req
        bucket = self.pick_bucket(len(req.prompt))
        t0 = self.clock()
        try:
            logits = self.runner.prefill(
                bucket, np.asarray(req.prompt, dtype=np.int32),
                len(req.prompt), slot.row)
        except Exception as e:  # poison the request, free the lane
            self._fail_slot(slot, e)
            return
        self.prefills += 1
        first = self.sampler(logits, req)
        req.tokens.append(first)
        self.tokens_generated += 1
        req.first_token_t = self.clock()
        ttft = req.first_token_t - req.submit_t
        self._ttfts.append(ttft)
        self._trace_event(req, "prefill", bucket=bucket,
                          prefill_s=req.first_token_t - t0, ttft_s=ttft)
        if _metrics.enabled():
            _metrics.histogram(
                "mxnet_serve_ttft_seconds",
                help="submit -> first generated token (prefill included)",
                buckets=_TTFT_BUCKETS).observe(ttft)
            _metrics.histogram(
                "mxnet_serve_prefill_seconds",
                help="wall time of one bucketed prefill call",
                buckets=_TTFT_BUCKETS).observe(req.first_token_t - t0)
        self._maybe_complete(slot)

    def _decode_once(self):
        with self._lock:
            active = [(i, s) for i, s in enumerate(self._slots)
                      if s is not None]
        if not active:
            return False
        g = self.geometry
        tokens = np.zeros(g.max_batch, dtype=np.int32)
        positions = np.zeros(g.max_batch, dtype=np.int32)
        tables = np.zeros((g.max_batch, g.max_pages_per_seq),
                          dtype=np.int32)
        for i, s in active:
            tokens[i] = s.req.tokens[-1]
            positions[i] = s.position
            tables[i] = s.row
        t0 = self.clock()
        try:
            logits = self.runner.decode(tokens, positions, tables)
        except Exception as e:
            for _, s in active:
                self._fail_slot(s, e)
            return True
        self.decode_steps += 1
        dt = self.clock() - t0
        # one flight event per batched step, not per request — decode is
        # the serve hot loop and the ring must outlast a request's life
        _flight.record("serve.decode", batch=len(active), dur=round(dt, 6))
        for i, s in active:
            s.position += 1
            tok = self.sampler(logits[i], s.req)
            s.req.tokens.append(tok)
            self.tokens_generated += 1
            self._tpots.append(dt)
            req = s.req
            if req.first_decode_t is None and len(req.tokens) >= 2:
                req.first_decode_t = self.clock()
                self._trace_event(
                    req, "first_decode",
                    first_decode_s=req.first_decode_t - req.first_token_t)
                if _metrics.enabled() and req.first_token_t is not None:
                    _metrics.histogram(
                        "mxnet_serve_first_decode_seconds",
                        help="first token -> first decode-step token "
                             "(TTFT breakdown: decode pipeline entry)",
                        buckets=_TPOT_BUCKETS,
                    ).observe(req.first_decode_t - req.first_token_t)
            self._maybe_complete(s)
        if _metrics.enabled():
            _metrics.histogram(
                "mxnet_serve_tpot_seconds",
                help="wall time of one batched decode step",
                buckets=_TPOT_BUCKETS).observe(dt)
            _metrics.counter(
                "mxnet_serve_decode_steps_total",
                help="batched decode steps executed").inc()
            _metrics.counter(
                "mxnet_serve_tokens_total",
                help="tokens generated across all requests",
            ).inc(len(active))
        return True

    # -- completion -------------------------------------------------------
    def _maybe_complete(self, slot):
        req = slot.req
        done = len(req.tokens) >= req.max_new_tokens
        if req.eos_id is not None and req.tokens \
                and req.tokens[-1] == req.eos_id:
            done = True
        if done:
            self._finish_slot(slot, error=None)

    def _fail_slot(self, slot, err):
        self._finish_slot(slot, error=err)

    def _finish_slot(self, slot, error):
        req = slot.req
        with self._lock:
            for i, s in enumerate(self._slots):
                if s is slot:
                    self._slots[i] = None
                    break
            self.arena.free(slot.pages, owner=req.rid)
            self.completed += 1
            self._count_req("failed" if error is not None else "completed")
            self._gauges_locked()
        req.error = error
        req.finish_t = self.clock()
        status = "failed" if error is not None else "completed"
        self._trace_event(req, "finish", status=status,
                          tokens=len(req.tokens),
                          error=(type(error).__name__ if error else ""))
        with self._trace_lock:
            tr = self._traces.get(req.trace_id)
            if tr is not None:
                tr["tokens"] = list(req.tokens)
                tr["breakdown"] = req.breakdown()
                if error is not None:
                    tr["error"] = str(error)
        req._done.set()

    # -- introspection ----------------------------------------------------
    def active_slots(self):
        with self._lock:
            return sum(1 for s in self._slots if s is not None)

    def queue_len(self):
        with self._lock:
            return len(self._queue)

    def has_work(self):
        with self._lock:
            return bool(self._queue) \
                or any(s is not None for s in self._slots)

    def wait_for_work(self, timeout):
        """Server-thread parking: wake on submit or after ``timeout``."""
        with self._work:
            if not self._queue and all(s is None for s in self._slots):
                self._work.wait(timeout)

    def percentile(self, which, q):
        """Exact percentile over the recent-window deques ('ttft'/'tpot')."""
        data = sorted(self._ttfts if which == "ttft" else self._tpots)
        if not data:
            return 0.0
        i = min(len(data) - 1, int(round(q * (len(data) - 1))))
        return data[i]

    def stats(self):
        with self._lock:
            active = sum(1 for s in self._slots if s is not None)
            qlen = len(self._queue)
        return {
            "admitted": self.admitted, "rejected": self.rejected,
            "completed": self.completed,
            "tokens_generated": self.tokens_generated,
            "decode_steps": self.decode_steps, "prefills": self.prefills,
            "active_slots": active, "queue_len": qlen,
            "arena_utilization": self.arena.utilization(),
            "ttft_p50_s": self.percentile("ttft", 0.50),
            "ttft_p99_s": self.percentile("ttft", 0.99),
            "tpot_p50_s": self.percentile("tpot", 0.50),
        }

    def _count_req(self, status):
        if _metrics.enabled():
            _metrics.counter(
                "mxnet_serve_requests_total",
                help="requests by outcome", status=status).inc()

    def _gauges_locked(self):
        if _metrics.enabled():
            _metrics.gauge(
                "mxnet_serve_queue_depth",
                help="requests waiting for admission").set(len(self._queue))
            _metrics.gauge(
                "mxnet_serve_batch_occupancy",
                help="active decode slots (of max_batch)",
            ).set(sum(1 for s in self._slots if s is not None))
