"""Paged-attention prefill/decode graphs + AOT serving bundles.

The serving tier never runs the gluon model: at export time the Llama
weights are pulled out of the block tree and baked as XLA constants into
two purpose-built graphs —

- ``prefill_<T>`` (one per sequence-length bucket): runs the whole
  prompt through full causal attention, scatters every K/V row into the
  paged arena, and returns the logits of the last real token;
- ``decode``: one token per active slot, batched over the server's
  fixed ``max_batch`` — RoPE at the slot's position, scatter into the
  page the block table names, then attention over the gathered pages.

On accelerator backends both donate the KV arena buffers (argnums 0/1),
so the steady-state decode loop updates the cache in place with zero
copies; on CPU donation is off by default because donated aliasing does
not survive executable serialization there (see _donate_kv).  The compiled
executables ship in a PR 7 ``MXAOT1`` bundle whose meta carries the
KV-page geometry; a serving process deserializes them at startup and
performs **zero live jits** (asserted by the serve-smoke CI job).

Numerics match ``gluon.model_zoo.llama`` exactly: RMSNorm in f32
(``lax.rsqrt``), rotate-half RoPE with the same inv-freq table, GQA via
post-projection head repeat — the paged decode's logits agree with the
full-sequence forward to float tolerance (tests/test_serve_e2e.py).
"""
from __future__ import annotations

import math
import os

import numpy as np

from ..base import MXNetError

BUNDLE_KIND = "serving"

# geometry fields a serving bundle must carry; the load-time validator
# refuses a bundle missing any of them (satellite: fail at load, not
# inside XLA on the first mismatched decode)
_GEOM_INT_FIELDS = ("num_layers", "num_heads", "num_kv_heads", "head_dim",
                    "units", "hidden_size", "vocab_size", "page_size",
                    "num_pages", "max_pages_per_seq", "max_batch")


class KVGeometry:
    """Shape contract between exporter, arena, scheduler and executables.

    Everything the serving process must agree on with the bundle lives
    here: the paged-KV layout (``page_size`` tokens per page,
    ``num_pages`` total — page 0 is reserved as the null page inactive
    slots scribble on), the decode batch width ``max_batch`` the
    executable was compiled for, and the prefill bucket ladder.
    """

    def __init__(self, num_layers, num_heads, num_kv_heads, head_dim,
                 units, hidden_size, vocab_size, page_size, num_pages,
                 max_pages_per_seq, max_batch, prefill_buckets,
                 dtype="float32", rope_base=10000.0, eps=1e-6,
                 tie_embeddings=False):
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.units = int(units)
        self.hidden_size = int(hidden_size)
        self.vocab_size = int(vocab_size)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.max_batch = int(max_batch)
        self.prefill_buckets = tuple(sorted(int(b) for b in prefill_buckets))
        self.dtype = str(dtype)
        self.rope_base = float(rope_base)
        self.eps = float(eps)
        self.tie_embeddings = bool(tie_embeddings)
        self.validate()

    @property
    def max_context(self):
        """Tokens addressable per sequence (prompt + generated)."""
        return self.max_pages_per_seq * self.page_size

    def validate(self):
        if self.page_size <= 0 or self.num_pages <= 1:
            raise MXNetError(
                "KV geometry needs page_size>0 and num_pages>1 (page 0 is "
                "the reserved null page); got page_size=%d num_pages=%d"
                % (self.page_size, self.num_pages))
        if self.max_batch <= 0 or self.max_pages_per_seq <= 0:
            raise MXNetError("KV geometry needs max_batch>0 and "
                             "max_pages_per_seq>0")
        if not self.prefill_buckets:
            raise MXNetError("KV geometry needs at least one prefill bucket")
        if self.prefill_buckets[-1] > self.max_context:
            raise MXNetError(
                "largest prefill bucket (%d) exceeds max context %d "
                "(= max_pages_per_seq %d x page_size %d)"
                % (self.prefill_buckets[-1], self.max_context,
                   self.max_pages_per_seq, self.page_size))
        if self.num_heads % self.num_kv_heads:
            raise MXNetError("num_heads must be a multiple of num_kv_heads")

    def to_dict(self):
        return {
            "num_layers": self.num_layers, "num_heads": self.num_heads,
            "num_kv_heads": self.num_kv_heads, "head_dim": self.head_dim,
            "units": self.units, "hidden_size": self.hidden_size,
            "vocab_size": self.vocab_size, "page_size": self.page_size,
            "num_pages": self.num_pages,
            "max_pages_per_seq": self.max_pages_per_seq,
            "max_batch": self.max_batch,
            "prefill_buckets": list(self.prefill_buckets),
            "dtype": self.dtype, "rope_base": self.rope_base,
            "eps": self.eps, "tie_embeddings": self.tie_embeddings,
        }

    @classmethod
    def from_dict(cls, d, origin="bundle"):
        missing = [f for f in _GEOM_INT_FIELDS if f not in d]
        if missing or "prefill_buckets" not in d:
            raise MXNetError(
                "%s: serving bundle geometry is missing %s — re-export "
                "with serve.export_serving_bundle"
                % (origin, ", ".join(missing) or "prefill_buckets"))
        return cls(**d)

    def kv_shape(self):
        """Arena buffer shape: (L, P, page, KV-heads, head-dim)."""
        return (self.num_layers, self.num_pages, self.page_size,
                self.num_kv_heads, self.head_dim)

    def describe(self):
        return ("layers=%d heads=%d/%d head_dim=%d pages=%dx%d "
                "max_batch=%d buckets=%s dtype=%s"
                % (self.num_layers, self.num_heads, self.num_kv_heads,
                   self.head_dim, self.num_pages, self.page_size,
                   self.max_batch, list(self.prefill_buckets), self.dtype))


def _env_int(name, default):
    v = os.environ.get(name, "")
    return int(v) if v.strip() else default


def default_buckets():
    """Prefill bucket ladder from MXNET_SERVE_BUCKETS (docs/env_vars.md)."""
    raw = os.environ.get("MXNET_SERVE_BUCKETS", "").strip()
    if not raw:
        return (32, 128, 512)
    try:
        return tuple(sorted({int(t) for t in raw.split(",") if t.strip()}))
    except ValueError:
        raise MXNetError("MXNET_SERVE_BUCKETS must be comma-separated ints, "
                         "got %r" % raw)


def geometry_from_net(net, page_size=None, num_pages=None, max_batch=None,
                      prefill_buckets=None, max_pages_per_seq=None):
    """Derive a :class:`KVGeometry` from a ``LlamaModel`` block tree,
    filling paging knobs from ``MXNET_SERVE_*`` env defaults."""
    blocks = list(net.blocks._children.values())
    if not blocks:
        raise MXNetError("model has no decoder blocks")
    attn = blocks[0].attn
    embed_w = net.embed.weight.data()
    page_size = page_size or _env_int("MXNET_SERVE_PAGE_SIZE", 16)
    num_pages = num_pages or _env_int("MXNET_SERVE_NUM_PAGES", 512)
    max_batch = max_batch or _env_int("MXNET_SERVE_MAX_BATCH", 8)
    buckets = tuple(prefill_buckets) if prefill_buckets \
        else default_buckets()
    if max_pages_per_seq is None:
        # default: one sequence may address half the arena, capped so the
        # bucket ladder always fits
        need = -(-max(buckets) // page_size)
        max_pages_per_seq = max(need + 1, (num_pages - 1) // 2)
    return KVGeometry(
        num_layers=len(blocks), num_heads=attn._heads,
        num_kv_heads=attn._kv_heads,
        head_dim=attn._units // attn._heads, units=net._units,
        hidden_size=blocks[0].ffn.gate.weight.shape[0],
        vocab_size=embed_w.shape[0], page_size=page_size,
        num_pages=num_pages, max_pages_per_seq=max_pages_per_seq,
        max_batch=max_batch, prefill_buckets=buckets,
        dtype=str(embed_w.dtype), rope_base=attn._base,
        eps=blocks[0].attn_norm._eps, tie_embeddings=net._tie)


def _pull(param):
    """Export-time weight pull — runs once per parameter per export, not
    on any serving path."""
    return param.data().asnumpy()  # mxlint: allow-host-sync


def extract_weights(net):
    """Pull the Llama weights out of the block tree as numpy arrays.

    Returns ``(embed, layers, norm, head)`` where ``layers`` is a list of
    per-block dicts; ``head`` is None for tied embeddings.  Dense weights
    keep the gluon (out, in) layout — the graphs apply ``x @ W.T``.
    """
    embed = _pull(net.embed.weight)
    layers = []
    for blk in net.blocks._children.values():
        layers.append({
            "attn_norm": _pull(blk.attn_norm.weight),
            "q": _pull(blk.attn.q_proj.weight),
            "k": _pull(blk.attn.k_proj.weight),
            "v": _pull(blk.attn.v_proj.weight),
            "o": _pull(blk.attn.o_proj.weight),
            "ffn_norm": _pull(blk.ffn_norm.weight),
            "gate": _pull(blk.ffn.gate.weight),
            "up": _pull(blk.ffn.up.weight),
            "down": _pull(blk.ffn.down.weight),
        })
    norm = _pull(net.norm.weight)
    head = None if net._tie else _pull(net.lm_head.weight)
    return embed, layers, norm, head


def _rmsnorm(x, gamma, eps):
    """f32-accumulated RMSNorm, bitwise-matching ops.nn.RMSNorm."""
    import jax.numpy as jnp
    from jax import lax

    x32 = x.astype(jnp.float32)
    y = x32 * lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
                        + eps)
    return (y * gamma.astype(jnp.float32)).astype(x.dtype)


def _rope_tables(positions, head_dim, base):
    """cos/sin tables (…, half) for rotate-half RoPE at ``positions``
    (float32, any leading shape) — same inv-freq form as llama._rope."""
    import jax.numpy as jnp

    half = head_dim // 2
    inv = jnp.arange(0, half, dtype=jnp.float32) * (-2.0 / head_dim)
    inv_freq = jnp.exp(inv * math.log(base))
    freqs = positions[..., None] * inv_freq
    return jnp.cos(freqs), jnp.sin(freqs)


def _rotate(x, cos, sin):
    """Rotate-half on (…, D); cos/sin broadcast over the head axis."""
    import jax.numpy as jnp

    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=-1)


def build_decode_fn(weights, geometry):
    """One batched decode step over the paged arena.

    Signature (all positional; kv buffers donated by the AOT compile
    when the backend supports it — see ``_donate_kv``):
    ``(kv_k, kv_v, tokens (B,) i32, positions (B,) i32,
    block_table (B, maxp) i32) -> (kv_k, kv_v, logits (B, V) f32)``.

    Inactive slots point their block-table row at the reserved null page
    0 with position 0 — their scatters land there harmlessly and their
    logits are discarded by the scheduler.
    """
    import jax
    import jax.numpy as jnp

    embed, layers, norm, head = weights
    g = geometry
    H, KV, D, S = g.num_heads, g.num_kv_heads, g.head_dim, g.page_size
    scale = 1.0 / math.sqrt(D)
    ctx = g.max_pages_per_seq * S

    def decode(kv_k, kv_v, tokens, positions, block_table):
        b = tokens.shape[0]
        x = embed[tokens]                                    # (B, U)
        cos, sin = _rope_tables(positions.astype(jnp.float32), D,
                                g.rope_base)                 # (B, half)
        cos, sin = cos[:, None, :], sin[:, None, :]          # (B, 1, half)
        rows = jnp.arange(b)
        pid = block_table[rows, positions // S]              # (B,)
        slot = positions % S
        valid = jnp.arange(ctx)[None, :] <= positions[:, None]  # (B, C)
        for li, lw in enumerate(layers):
            h = _rmsnorm(x, lw["attn_norm"], g.eps)
            q = _rotate((h @ lw["q"].T).reshape(b, H, D), cos, sin)
            k = _rotate((h @ lw["k"].T).reshape(b, KV, D), cos, sin)
            v = (h @ lw["v"].T).reshape(b, KV, D)
            kv_k = kv_k.at[li, pid, slot].set(k)
            kv_v = kv_v.at[li, pid, slot].set(v)
            # gather this sequence's pages: (B, maxp, S, KV, D) -> (B, C,…)
            keys = kv_k[li, block_table].reshape(b, ctx, KV, D)
            vals = kv_v[li, block_table].reshape(b, ctx, KV, D)
            keys = jnp.repeat(keys, H // KV, axis=2)         # GQA repeat
            vals = jnp.repeat(vals, H // KV, axis=2)
            scores = jnp.einsum("bhd,bchd->bhc", q, keys) * scale
            scores = jnp.where(valid[:, None, :],
                               scores.astype(jnp.float32), -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            att = jnp.einsum("bhc,bchd->bhd", probs, vals)
            x = x + att.reshape(b, H * D) @ lw["o"].T
            h2 = _rmsnorm(x, lw["ffn_norm"], g.eps)
            x = x + (jax.nn.silu(h2 @ lw["gate"].T)
                     * (h2 @ lw["up"].T)) @ lw["down"].T
        xh = _rmsnorm(x, norm, g.eps)
        hw = embed if head is None else head
        return kv_k, kv_v, (xh @ hw.T).astype(jnp.float32)

    return decode


def build_prefill_fn(weights, geometry, bucket):
    """Whole-prompt pass for one padded bucket length ``T``.

    ``(kv_k, kv_v, tokens (T,) i32, length () i32,
    block_table (maxp,) i32) -> (kv_k, kv_v, logits (V,) f32)``.

    Every position's K/V is scattered into the arena (pad positions land
    on the null page or on this sequence's own not-yet-read slots, both
    harmless); the returned logits are the last REAL token's — the first
    generated token comes straight out of prefill.
    """
    import jax
    import jax.numpy as jnp

    embed, layers, norm, head = weights
    g = geometry
    H, KV, D, S = g.num_heads, g.num_kv_heads, g.head_dim, g.page_size
    scale = 1.0 / math.sqrt(D)
    t = int(bucket)

    def prefill(kv_k, kv_v, tokens, length, block_table):
        x = embed[tokens]                                    # (T, U)
        pos = jnp.arange(t)
        cos, sin = _rope_tables(pos.astype(jnp.float32), D, g.rope_base)
        cos, sin = cos[:, None, :], sin[:, None, :]          # (T, 1, half)
        pid = block_table[pos // S]                          # (T,)
        slot = pos % S
        causal = (pos[None, :] <= pos[:, None]) \
            & (pos[None, :] < length)                        # (T, T)
        for li, lw in enumerate(layers):
            h = _rmsnorm(x, lw["attn_norm"], g.eps)
            q = _rotate((h @ lw["q"].T).reshape(t, H, D), cos, sin)
            k = _rotate((h @ lw["k"].T).reshape(t, KV, D), cos, sin)
            v = (h @ lw["v"].T).reshape(t, KV, D)
            kv_k = kv_k.at[li, pid, slot].set(k)
            kv_v = kv_v.at[li, pid, slot].set(v)
            keys = jnp.repeat(k, H // KV, axis=1)            # (T, H, D)
            vals = jnp.repeat(v, H // KV, axis=1)
            scores = jnp.einsum("thd,uhd->htu", q, keys) * scale
            scores = jnp.where(causal[None, :, :],
                               scores.astype(jnp.float32), -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            att = jnp.einsum("htu,uhd->thd", probs, vals)
            x = x + att.reshape(t, H * D) @ lw["o"].T
            h2 = _rmsnorm(x, lw["ffn_norm"], g.eps)
            x = x + (jax.nn.silu(h2 @ lw["gate"].T)
                     * (h2 @ lw["up"].T)) @ lw["down"].T
        xh = _rmsnorm(x, norm, g.eps)
        last = jnp.take(xh, length - 1, axis=0)              # (U,)
        hw = embed if head is None else head
        return kv_k, kv_v, (last @ hw.T).astype(jnp.float32)

    return prefill


def _donate_kv():
    """Should the serving executables donate the KV buffers (args 0, 1)?

    ``MXNET_SERVE_AOT_DONATE`` = ``1`` forces on, ``0`` forces off,
    unset/``auto`` donates everywhere EXCEPT the CPU backend.  On CPU
    (jax 0.4.37) an executable that carries input-output aliasing does
    not survive ``serialize_executable`` → ``deserialize_and_load``:
    the reloaded binary's aliasing metadata is wrong and every run
    corrupts the allocator heap — results stay correct but the process
    dies with ``corrupted double-linked list`` / SIGSEGV at teardown
    (~50% of runs; bisected fresh-vs-deserialized × donate-vs-not, only
    the deserialized+donated cell fails).  Donation-free decode costs
    one KV-arena copy per step, which CPU serving (tests, smoke CI)
    can afford; accelerator backends keep the zero-copy path.
    """
    mode = os.environ.get("MXNET_SERVE_AOT_DONATE", "auto").lower()
    if mode in ("1", "true"):
        return True
    if mode in ("0", "false"):
        return False
    import jax

    return jax.default_backend() != "cpu"


def _aot_compile(fn, avals):
    """jit → lower → compile, KV buffers (argnums 0, 1) donated when
    the backend supports aliasing across serialization (_donate_kv)."""
    import jax

    kwargs = {"donate_argnums": (0, 1)} if _donate_kv() else {}
    return jax.jit(fn, **kwargs).lower(*avals).compile()


def compile_serving_executables(net, geometry):
    """Build + AOT-compile the decode and per-bucket prefill graphs.

    Returns ``{name: jax.stages.Compiled}`` with weights baked in as
    constants — the bundle is self-contained, no .params sidecar.
    """
    import jax

    g = geometry
    raw = extract_weights(net)
    from ..telemetry import memdump as _memdump

    def dev(a):
        buf = jax.device_put(np.asarray(a, dtype=g.dtype))
        _memdump.tag(buf, origin="param", label="serving_weight")
        return buf
    weights = (dev(raw[0]), [{k: dev(v) for k, v in lw.items()}
                             for lw in raw[1]], dev(raw[2]),
               None if raw[3] is None else dev(raw[3]))
    kv = jax.ShapeDtypeStruct(g.kv_shape(), np.dtype(g.dtype))
    i32 = np.dtype(np.int32)
    exes = {}
    dec_avals = (kv, kv, jax.ShapeDtypeStruct((g.max_batch,), i32),
                 jax.ShapeDtypeStruct((g.max_batch,), i32),
                 jax.ShapeDtypeStruct((g.max_batch, g.max_pages_per_seq),
                                      i32))
    exes["decode"] = _aot_compile(build_decode_fn(weights, g), dec_avals)
    for b in g.prefill_buckets:
        pf_avals = (kv, kv, jax.ShapeDtypeStruct((b,), i32),
                    jax.ShapeDtypeStruct((), i32),
                    jax.ShapeDtypeStruct((g.max_pages_per_seq,), i32))
        exes["prefill_%d" % b] = _aot_compile(
            build_prefill_fn(weights, g, b), pf_avals)
    return exes


def export_serving_bundle(net, path, page_size=None, num_pages=None,
                          max_batch=None, prefill_buckets=None,
                          max_pages_per_seq=None, mesh=None):
    """Export ``net`` as a self-contained MXAOT1 serving bundle.

    The bundle carries the AOT-compiled decode + per-bucket prefill
    executables (weights baked in) and the :class:`KVGeometry` in its
    meta, so ``serve.LlamaServer(path)`` starts with zero live compiles.
    Paging knobs default from ``MXNET_SERVE_*`` (docs/env_vars.md).
    Returns the geometry.

    ``mesh`` (a Mesh / axes dict — abstract, no devices needed) runs the
    auto-sharding planner over the weight tree and stores its decision
    under ``meta["planner"]`` — chosen per-weight specs plus a suggested
    KV-arena spec — so a sharded server can be brought up from the
    bundle with zero live jits AND zero hand-written specs
    (``planner.plan_serving``).  The executables themselves stay
    single-device; the planner meta is advisory placement data.
    """
    from .. import compile_cache as _ccache

    g = geometry_from_net(net, page_size=page_size, num_pages=num_pages,
                          max_batch=max_batch,
                          prefill_buckets=prefill_buckets,
                          max_pages_per_seq=max_pages_per_seq)
    meta = {"kind": BUNDLE_KIND, "geometry": g.to_dict()}
    if mesh is not None:
        from .. import planner as _planner

        meta["planner"] = _planner.plan_serving(net, g, mesh)
    exes = compile_serving_executables(net, g)
    entries = {name: _ccache.serialize_compiled(c)
               for name, c in exes.items()}
    _ccache.save_bundle(path, entries, meta=meta)
    return g


def read_bundle_geometry(path):
    """Parse + validate a serving bundle's KV geometry WITHOUT
    deserializing any executable (cheap inspection: Predictor's
    redirect error, doctor tools).  Returns ``(KVGeometry, doc)``."""
    from .. import compile_cache as _ccache

    doc = _ccache.load_bundle(path)
    meta = doc.get("meta", {})
    if meta.get("kind") != BUNDLE_KIND:
        raise MXNetError(
            "%s is not a serving bundle (kind=%r) — export one with "
            "serve.export_serving_bundle(net, path)"
            % (path, meta.get("kind")))
    return KVGeometry.from_dict(meta.get("geometry", {}), origin=path), doc


def load_serving_executables(path, expect=None):
    """Load a serving bundle: ``(KVGeometry, {name: Compiled})``.

    Validation happens HERE, not on the first decode: the bundle must be
    a serving bundle, its meta must carry a complete geometry, every
    executable named by the geometry must be present, and — when the
    caller passes ``expect`` (a KVGeometry or partial dict) — the
    KV-page geometry must agree field by field, each mismatch named in
    the error.
    """
    from .. import compile_cache as _ccache

    g, doc = read_bundle_geometry(path)
    if expect is not None:
        check_geometry(g, expect, origin=path)
    want = ["decode"] + ["prefill_%d" % b for b in g.prefill_buckets]
    entries = doc.get("entries", {})
    missing = [n for n in want if n not in entries]
    if missing:
        raise MXNetError("%s: serving bundle is missing executables %s "
                         "for geometry [%s]"
                         % (path, missing, g.describe()))
    exes = {n: _ccache.deserialize_compiled(entries[n]) for n in want}
    return g, exes


def check_geometry(got, expect, origin="bundle"):
    """Field-by-field KV geometry comparison with a clear error.

    ``expect``: KVGeometry or a dict of the subset to pin (e.g.
    ``{"page_size": 16, "dtype": "float32"}``).
    """
    exp = expect.to_dict() if isinstance(expect, KVGeometry) else dict(expect)
    gd = got.to_dict()
    bad = []
    for field, want in exp.items():
        if field not in gd:
            raise MXNetError("%s: unknown geometry field %r" % (origin,
                                                                field))
        have = gd[field]
        if field == "prefill_buckets":
            want = list(want)
        if have != want:
            bad.append("%s: bundle has %r, caller expects %r"
                       % (field, have, want))
    if bad:
        raise MXNetError(
            "%s: KV-page geometry mismatch — refusing to serve (this "
            "would fail inside XLA on the first decode):\n  %s"
            % (origin, "\n  ".join(bad)))
