"""Paged-attention prefill/decode graphs + AOT serving bundles.

The serving tier never runs the gluon model: at export time the Llama
weights are pulled out of the block tree and baked as XLA constants into
two purpose-built graphs —

- ``prefill_<T>`` (one per sequence-length bucket): runs the whole
  prompt through full causal attention, scatters every K/V row into the
  paged arena, and returns the logits of the last real token;
- ``decode``: one token per active slot, batched over the server's
  fixed ``max_batch`` — RoPE at the slot's position, scatter into the
  page the block table names, then attention over the gathered pages;
- ``verify`` (when the geometry carries ``spec_k > 0``): the
  speculative-decoding signature — ``spec_k + 1`` tokens per lane (the
  last accepted token plus ``spec_k`` n-gram drafts), scattered and
  attended causally in one call, returning per-position logits so the
  scheduler can accept the longest exactly-matching draft prefix
  (ISSUE 13; Leviathan et al.).  Verify-K over tokens ``t..t+K`` is
  *exactly* K+1 sequential decodes: each query position only attends
  KV rows at or before its own position, rejected drafts' garbage rows
  sit beyond every accepted query's mask and are overwritten by the
  next call before anything reads them.

The arena stores KV in the model dtype or — when the geometry says
``kv_dtype="int8"`` — as int8 pages with one float32 scale per
``(layer, page)``.  Quantization happens on append inside the compiled
graphs: the row written to a page's **slot 0** fixes that page's scale
(its own absmax with 2x headroom) and later rows in the page quantize
against it, never rescaling what is already stored.  That makes the
quantized arena state a pure function of the token sequence —
independent of how tokens were grouped into prefill/decode/verify calls
— which is what lets the spec-on and spec-off greedy outputs stay
token-for-token identical at int8.  Page reuse is safe for free: a new
owner's first write to a page is always that page's slot 0 (positions
are written in order), which resets the scale.

On accelerator backends both donate the KV arena buffers (argnums 0/1),
so the steady-state decode loop updates the cache in place with zero
copies; on CPU donation is off by default because donated aliasing does
not survive executable serialization there (see _donate_kv).  The compiled
executables ship in a PR 7 ``MXAOT1`` bundle whose meta carries the
KV-page geometry; a serving process deserializes them at startup and
performs **zero live jits** (asserted by the serve-smoke CI job).

Numerics match ``gluon.model_zoo.llama`` exactly: RMSNorm in f32
(``lax.rsqrt``), rotate-half RoPE with the same inv-freq table, GQA via
post-projection head repeat — the paged decode's logits agree with the
full-sequence forward to float tolerance (tests/test_serve_e2e.py).
"""
from __future__ import annotations

import math
import os

import numpy as np

from ..base import MXNetError

BUNDLE_KIND = "serving"

# geometry fields a serving bundle must carry; the load-time validator
# refuses a bundle missing any of them (satellite: fail at load, not
# inside XLA on the first mismatched decode).  kv_dtype/spec_k are NOT
# in this list: pre-PR-13 bundles lack them and must keep loading
# (defaulting to an fp32 arena with speculation off).
_GEOM_INT_FIELDS = ("num_layers", "num_heads", "num_kv_heads", "head_dim",
                    "units", "hidden_size", "vocab_size", "page_size",
                    "num_pages", "max_pages_per_seq", "max_batch")

# int8 paged-KV quantization constants.  A page's scale is fixed by its
# slot-0 row's absmax with this headroom (later rows clip past it);
# 2x keeps one extra bit of range for K/V magnitude drift within a page
# at the cost of one bit of precision.
_INT8_QMAX = 127.0
_INT8_SCALE_HEADROOM = 2.0
_INT8_MIN_SCALE = 1e-8  # an all-zero slot-0 row must not divide by zero


class KVGeometry:
    """Shape contract between exporter, arena, scheduler and executables.

    Everything the serving process must agree on with the bundle lives
    here: the paged-KV layout (``page_size`` tokens per page,
    ``num_pages`` total — page 0 is reserved as the null page inactive
    slots scribble on), the decode batch width ``max_batch`` the
    executable was compiled for, and the prefill bucket ladder.
    """

    def __init__(self, num_layers, num_heads, num_kv_heads, head_dim,
                 units, hidden_size, vocab_size, page_size, num_pages,
                 max_pages_per_seq, max_batch, prefill_buckets,
                 dtype="float32", rope_base=10000.0, eps=1e-6,
                 tie_embeddings=False, kv_dtype=None, spec_k=0,
                 paged_kernel=None, prefill_chunk=0):
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.units = int(units)
        self.hidden_size = int(hidden_size)
        self.vocab_size = int(vocab_size)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.max_batch = int(max_batch)
        self.prefill_buckets = tuple(sorted(int(b) for b in prefill_buckets))
        self.dtype = str(dtype)
        self.rope_base = float(rope_base)
        self.eps = float(eps)
        self.tie_embeddings = bool(tie_embeddings)
        # PR 13 fields with pre-PR-13 defaults: an old bundle dict that
        # carries neither loads as an fp32 arena with speculation off
        self.kv_dtype = str(kv_dtype) if kv_dtype else self.dtype
        self.spec_k = int(spec_k)
        # ISSUE 19: chunked-prefill width.  > 0 additionally compiles a
        # batched mid-sequence ``chunk`` executable (the step graph at
        # k1=prefill_chunk) so long / over-bucket prompts prefill in
        # ladder-sized chunks interleaved with decode steps, and cached
        # prefix splices resume mid-sequence.  0 = off; old bundle
        # dicts lack the field and load with it off.
        self.prefill_chunk = int(prefill_chunk)
        # PR 14: which decode/verify attention the executables were
        # BUILT with — "auto" (Pallas kernel on TPU, XLA reference
        # elsewhere), "1" (kernel forced; interpreter off-TPU), "0"
        # (reference forced).  Baked at export: a bundle records the
        # choice in its meta, a loaded server inherits it.  Old bundle
        # dicts lack the field and load as "auto".
        if paged_kernel is None or paged_kernel == "":
            paged_kernel = "auto"
        if isinstance(paged_kernel, bool) or isinstance(paged_kernel, int):
            paged_kernel = str(int(paged_kernel))
        self.paged_kernel = str(paged_kernel).lower()
        self.validate()

    @property
    def max_context(self):
        """Tokens addressable per sequence (prompt + generated)."""
        return self.max_pages_per_seq * self.page_size

    def validate(self):
        if self.page_size <= 0 or self.num_pages <= 1:
            raise MXNetError(
                "KV geometry needs page_size>0 and num_pages>1 (page 0 is "
                "the reserved null page); got page_size=%d num_pages=%d"
                % (self.page_size, self.num_pages))
        if self.max_batch <= 0 or self.max_pages_per_seq <= 0:
            raise MXNetError("KV geometry needs max_batch>0 and "
                             "max_pages_per_seq>0")
        if not self.prefill_buckets:
            raise MXNetError("KV geometry needs at least one prefill bucket")
        if self.prefill_buckets[-1] > self.max_context:
            raise MXNetError(
                "largest prefill bucket (%d) exceeds max context %d "
                "(= max_pages_per_seq %d x page_size %d)"
                % (self.prefill_buckets[-1], self.max_context,
                   self.max_pages_per_seq, self.page_size))
        if self.num_heads % self.num_kv_heads:
            raise MXNetError("num_heads must be a multiple of num_kv_heads")
        if self.kv_dtype not in (self.dtype, "int8"):
            raise MXNetError(
                "kv_dtype must be the model dtype (%r) or 'int8', got %r"
                % (self.dtype, self.kv_dtype))
        if not 0 <= self.spec_k <= 64:
            raise MXNetError("spec_k must be in [0, 64] (draft tokens "
                             "verified per decode call), got %d"
                             % self.spec_k)
        if self.paged_kernel not in ("auto", "0", "1"):
            raise MXNetError(
                "paged_kernel must be 'auto', '0' or '1' (see "
                "MXNET_SERVE_PAGED_KERNEL in docs/env_vars.md), got %r"
                % self.paged_kernel)
        if self.prefill_chunk < 0 or self.prefill_chunk > self.max_context:
            raise MXNetError(
                "prefill_chunk must be in [0, max_context=%d] (0 "
                "disables chunked prefill), got %d"
                % (self.max_context, self.prefill_chunk))

    def to_dict(self):
        return {
            "num_layers": self.num_layers, "num_heads": self.num_heads,
            "num_kv_heads": self.num_kv_heads, "head_dim": self.head_dim,
            "units": self.units, "hidden_size": self.hidden_size,
            "vocab_size": self.vocab_size, "page_size": self.page_size,
            "num_pages": self.num_pages,
            "max_pages_per_seq": self.max_pages_per_seq,
            "max_batch": self.max_batch,
            "prefill_buckets": list(self.prefill_buckets),
            "dtype": self.dtype, "rope_base": self.rope_base,
            "eps": self.eps, "tie_embeddings": self.tie_embeddings,
            "kv_dtype": self.kv_dtype, "spec_k": self.spec_k,
            "paged_kernel": self.paged_kernel,
            "prefill_chunk": self.prefill_chunk,
        }

    @classmethod
    def from_dict(cls, d, origin="bundle"):
        missing = [f for f in _GEOM_INT_FIELDS if f not in d]
        if missing or "prefill_buckets" not in d:
            raise MXNetError(
                "%s: serving bundle geometry is missing %s — re-export "
                "with serve.export_serving_bundle"
                % (origin, ", ".join(missing) or "prefill_buckets"))
        return cls(**d)

    def kv_shape(self):
        """Arena buffer shape: (L, P, page, KV-heads, head-dim)."""
        return (self.num_layers, self.num_pages, self.page_size,
                self.num_kv_heads, self.head_dim)

    # the fields a replacement bundle must agree on for an in-place
    # hot-swap (``LlamaServer.reload``): everything the scheduler and
    # the queued requests already depend on — paging layout, batch
    # width, bucket ladder, vocabulary, arena dtype and verify width.
    # Model internals (layers, heads, weights) are free to change: the
    # arena is rebuilt from the new geometry and the executables are
    # self-contained.
    HOT_SWAP_FIELDS = ("page_size", "num_pages", "max_pages_per_seq",
                       "max_batch", "prefill_buckets", "vocab_size",
                       "kv_dtype", "spec_k", "prefill_chunk")

    def hot_swap_pins(self):
        """The geometry subset ``reload()`` pins (``check_geometry``
        dict) — a candidate bundle mismatching any of these would strand
        queued requests or tear live block tables."""
        d = self.to_dict()
        return {f: d[f] for f in self.HOT_SWAP_FIELDS}

    @property
    def quantized(self):
        """True when the arena stores int8 pages with per-page scales."""
        return self.kv_dtype == "int8"

    def scale_shape(self):
        """Per-page quantization scale shape: (L, pages); one float32
        scale per (layer, page) for each of K and V."""
        return (self.num_layers, self.num_pages)

    def describe(self):
        return ("layers=%d heads=%d/%d head_dim=%d pages=%dx%d "
                "max_batch=%d buckets=%s dtype=%s kv_dtype=%s spec_k=%d "
                "paged_kernel=%s prefill_chunk=%d"
                % (self.num_layers, self.num_heads, self.num_kv_heads,
                   self.head_dim, self.num_pages, self.page_size,
                   self.max_batch, list(self.prefill_buckets), self.dtype,
                   self.kv_dtype, self.spec_k, self.paged_kernel,
                   self.prefill_chunk))


def _env_int(name, default):
    v = os.environ.get(name, "")
    return int(v) if v.strip() else default


def default_buckets():
    """Prefill bucket ladder from MXNET_SERVE_BUCKETS (docs/env_vars.md)."""
    raw = os.environ.get("MXNET_SERVE_BUCKETS", "").strip()
    if not raw:
        return (32, 128, 512)
    try:
        return tuple(sorted({int(t) for t in raw.split(",") if t.strip()}))
    except ValueError:
        raise MXNetError("MXNET_SERVE_BUCKETS must be comma-separated ints, "
                         "got %r" % raw)


def geometry_from_net(net, page_size=None, num_pages=None, max_batch=None,
                      prefill_buckets=None, max_pages_per_seq=None,
                      kv_dtype=None, spec_k=None, paged_kernel=None,
                      prefill_chunk=None):
    """Derive a :class:`KVGeometry` from a ``LlamaModel`` block tree,
    filling paging knobs from ``MXNET_SERVE_*`` env defaults."""
    blocks = list(net.blocks._children.values())
    if not blocks:
        raise MXNetError("model has no decoder blocks")
    attn = blocks[0].attn
    embed_w = net.embed.weight.data()
    page_size = page_size or _env_int("MXNET_SERVE_PAGE_SIZE", 16)
    num_pages = num_pages or _env_int("MXNET_SERVE_NUM_PAGES", 512)
    max_batch = max_batch or _env_int("MXNET_SERVE_MAX_BATCH", 8)
    kv_dtype = kv_dtype \
        or os.environ.get("MXNET_SERVE_KV_DTYPE", "").strip() or None
    spec_k = spec_k if spec_k is not None \
        else _env_int("MXNET_SERVE_SPEC_K", 0)
    prefill_chunk = prefill_chunk if prefill_chunk is not None \
        else _env_int("MXNET_SERVE_PREFILL_CHUNK", 0)
    if paged_kernel is None:
        paged_kernel = os.environ.get("MXNET_SERVE_PAGED_KERNEL",
                                      "").strip() or None
    buckets = tuple(prefill_buckets) if prefill_buckets \
        else default_buckets()
    if max_pages_per_seq is None:
        # default: a full batch can at most address the whole arena. The
        # block-table width is also the attention context every decode /
        # verify call gathers, so an over-wide table (the old default let
        # one lane claim half the arena) taxes every step with mostly-null
        # pages. Floored so the bucket ladder always fits.
        need = -(-max(buckets) // page_size)
        max_pages_per_seq = max(need + 1, num_pages // max_batch)
    return KVGeometry(
        num_layers=len(blocks), num_heads=attn._heads,
        num_kv_heads=attn._kv_heads,
        head_dim=attn._units // attn._heads, units=net._units,
        hidden_size=blocks[0].ffn.gate.weight.shape[0],
        vocab_size=embed_w.shape[0], page_size=page_size,
        num_pages=num_pages, max_pages_per_seq=max_pages_per_seq,
        max_batch=max_batch, prefill_buckets=buckets,
        dtype=str(embed_w.dtype), rope_base=attn._base,
        eps=blocks[0].attn_norm._eps, tie_embeddings=net._tie,
        kv_dtype=kv_dtype, spec_k=spec_k, paged_kernel=paged_kernel,
        prefill_chunk=prefill_chunk)


def _pull(param):
    """Export-time weight pull — runs once per parameter per export, not
    on any serving path."""
    return param.data().asnumpy()  # mxlint: allow-host-sync


def extract_weights(net):
    """Pull the Llama weights out of the block tree as numpy arrays.

    Returns ``(embed, layers, norm, head)`` where ``layers`` is a list of
    per-block dicts; ``head`` is None for tied embeddings.  Dense weights
    keep the gluon (out, in) layout — the graphs apply ``x @ W.T``.
    """
    embed = _pull(net.embed.weight)
    layers = []
    for blk in net.blocks._children.values():
        layers.append({
            "attn_norm": _pull(blk.attn_norm.weight),
            "q": _pull(blk.attn.q_proj.weight),
            "k": _pull(blk.attn.k_proj.weight),
            "v": _pull(blk.attn.v_proj.weight),
            "o": _pull(blk.attn.o_proj.weight),
            "ffn_norm": _pull(blk.ffn_norm.weight),
            "gate": _pull(blk.ffn.gate.weight),
            "up": _pull(blk.ffn.up.weight),
            "down": _pull(blk.ffn.down.weight),
        })
    norm = _pull(net.norm.weight)
    head = None if net._tie else _pull(net.lm_head.weight)
    return embed, layers, norm, head


def _rmsnorm(x, gamma, eps):
    """f32-accumulated RMSNorm, bitwise-matching ops.nn.RMSNorm."""
    import jax.numpy as jnp
    from jax import lax

    x32 = x.astype(jnp.float32)
    y = x32 * lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
                        + eps)
    return (y * gamma.astype(jnp.float32)).astype(x.dtype)


def _rope_tables(positions, head_dim, base):
    """cos/sin tables (…, half) for rotate-half RoPE at ``positions``
    (float32, any leading shape) — same inv-freq form as llama._rope."""
    import jax.numpy as jnp

    half = head_dim // 2
    inv = jnp.arange(0, half, dtype=jnp.float32) * (-2.0 / head_dim)
    inv_freq = jnp.exp(inv * math.log(base))
    freqs = positions[..., None] * inv_freq
    return jnp.cos(freqs), jnp.sin(freqs)


def _rotate(x, cos, sin):
    """Rotate-half on (…, D); cos/sin broadcast over the head axis."""
    import jax.numpy as jnp

    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=-1)


def build_step_fn(weights, geometry, k1):
    """``k1`` tokens per lane through the paged arena in one call.

    This is the shared body of ``decode`` (``k1=1``) and ``verify``
    (``k1=spec_k+1``).  Signature (all positional; kv buffers — and the
    scale arrays for int8 — donated by the AOT compile when the backend
    supports it, see ``_donate_kv``):

    - fp32: ``(kv_k, kv_v, tokens (B, k1) i32, positions (B,) i32,
      block_table (B, maxp) i32) -> (kv_k, kv_v, logits (B, k1, V))``
    - int8: ``(kv_k, kv_v, k_scale (L, P) f32, v_scale (L, P) f32,
      tokens, positions, block_table) -> (kv_k, kv_v, k_scale, v_scale,
      logits)``

    Lane ``b``'s token ``j`` sits at position ``positions[b] + j``;
    query ``j`` attends context ``<= positions[b] + j`` only, so the
    per-position logits equal what ``k1`` sequential single-token
    decodes would produce (the exactness speculative acceptance rides
    on).  Inactive slots point their block-table row at the reserved
    null page 0 with position 0 — their scatters land there harmlessly
    (every lane writes the same pad-token rows, so even the duplicate
    null-page scatters are deterministic) and their logits are
    discarded by the scheduler.

    Int8 append: the row landing on a page's slot 0 fixes the page
    scale (own absmax x headroom / 127); rows landing further into a
    page quantize against the page's current scale — the scale of its
    slot-0 write, whether that write happened in this call (the
    ``start >= 0`` branch below) or in an earlier one.  Nothing already
    stored is ever requantized, so arena bytes after token t are
    independent of call grouping.
    """
    import jax
    import jax.numpy as jnp

    from ..ops.paged_attention import paged_attention as _paged_attn

    embed, layers, norm, head = weights
    g = geometry
    H, KV, D, S = g.num_heads, g.num_kv_heads, g.head_dim, g.page_size
    scale = 1.0 / math.sqrt(D)
    ctx = g.max_pages_per_seq * S
    int8 = g.quantized
    jidx = jnp.arange(k1)
    # attention path, resolved at BUILD time (the executable is AOT-
    # compiled for the default backend, so there is nothing to defer):
    # "1" forces the Pallas kernel (interpreter off-TPU — it traces to
    # plain jax ops and serializes into the bundle, the CI parity
    # path), "0" forces the gather + grouped-einsum reference, "auto"
    # takes the kernel on TPU and the reference elsewhere.
    kernel = g.paged_kernel == "1" or (
        g.paged_kernel == "auto" and jax.default_backend() == "tpu")

    def append(kv, sc, li, pid, slot, rows):
        """Scatter ``rows`` (B, k1, KV, D) at (li, pid, slot); quantize
        against per-page scales when the arena is int8."""
        if not int8:
            return kv.at[li, pid, slot].set(rows), sc
        r32 = rows.astype(jnp.float32)
        amax = jnp.max(jnp.abs(r32), axis=(2, 3))            # (B, k1)
        # in-call page starts: token j's page began at call offset
        # j - slot[j]; negative means the page's slot 0 was written by
        # an earlier call and its stored scale rules
        start = jidx[None, :] - slot                         # (B, k1)
        first = jnp.take_along_axis(amax, jnp.clip(start, 0, k1 - 1),
                                    axis=1)
        news = jnp.where(start >= 0,
                         first * (_INT8_SCALE_HEADROOM / _INT8_QMAX),
                         sc[li, pid])
        news = jnp.maximum(news, _INT8_MIN_SCALE)
        q = jnp.clip(jnp.round(r32 / news[..., None, None]),
                     -_INT8_QMAX, _INT8_QMAX).astype(jnp.int8)
        # rows of one page all write the page's resolved scale — equal
        # values, so duplicate scatter order cannot matter
        return kv.at[li, pid, slot].set(q), sc.at[li, pid].set(news)

    def gather(kv, sc, li, block_table, b, dt):
        """This lane's pages as (B, C, KV, D) in the model dtype."""
        pages = kv[li, block_table]            # (B, maxp, S, KV, D)
        if int8:
            ps = sc[li, block_table]           # (B, maxp)
            pages = (pages.astype(jnp.float32)
                     * ps[..., None, None, None]).astype(dt)
        return pages.reshape(b, ctx, KV, D)

    def step(kv_k, kv_v, *rest):
        if int8:
            k_sc, v_sc, tokens, positions, block_table = rest
        else:
            tokens, positions, block_table = rest
            k_sc = v_sc = None
        b = tokens.shape[0]
        x = embed[tokens]                                    # (B, k1, U)
        pos = positions[:, None] + jidx[None, :]             # (B, k1)
        cos, sin = _rope_tables(pos.astype(jnp.float32), D,
                                g.rope_base)                 # (B, k1, half)
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
        rows_b = jnp.arange(b)
        pid = block_table[rows_b[:, None], pos // S]         # (B, k1)
        slot = pos % S
        valid = jnp.arange(ctx)[None, None, :] <= pos[..., None]
        for li, lw in enumerate(layers):
            h = _rmsnorm(x, lw["attn_norm"], g.eps)
            q = _rotate((h @ lw["q"].T).reshape(b, k1, H, D), cos, sin)
            k = _rotate((h @ lw["k"].T).reshape(b, k1, KV, D), cos, sin)
            v = (h @ lw["v"].T).reshape(b, k1, KV, D)
            kv_k, k_sc = append(kv_k, k_sc, li, pid, slot, k)
            kv_v, v_sc = append(kv_v, v_sc, li, pid, slot, v)
            if kernel:
                # fused gather + dequant + online-softmax attention
                # straight off the arena's pages — no (B, ctx, KV, D)
                # HBM materialization, no fp32 dequant copy, no GQA
                # replication (ops/paged_attention.py)
                sc_args = (k_sc[li], v_sc[li]) if int8 else ()
                att = _paged_attn(q, kv_k[li], kv_v[li], block_table,
                                  positions, *sc_args, scale=scale,
                                  use_kernel=1)
            else:
                # XLA reference: still gathers the context, but attends
                # grouped heads (B, k1, KV, G, ctx) directly — K/V are
                # never replicated H/KV-fold (bitwise-identical logits
                # to the old jnp.repeat form, tests/test_paged_attention
                # .py::test_grouped_einsum_matches_repeat_bitwise)
                keys = gather(kv_k, k_sc, li, block_table, b, x.dtype)
                vals = gather(kv_v, v_sc, li, block_table, b, x.dtype)
                qg = q.reshape(b, k1, KV, H // KV, D)
                scores = jnp.einsum("bkvgd,bcvd->bkvgc", qg, keys) * scale
                scores = jnp.where(valid[:, :, None, None, :],
                                   scores.astype(jnp.float32), -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
                att = jnp.einsum("bkvgc,bcvd->bkvgd", probs, vals) \
                    .reshape(b, k1, H, D)
            x = x + att.reshape(b, k1, H * D) @ lw["o"].T
            h2 = _rmsnorm(x, lw["ffn_norm"], g.eps)
            x = x + (jax.nn.silu(h2 @ lw["gate"].T)
                     * (h2 @ lw["up"].T)) @ lw["down"].T
        xh = _rmsnorm(x, norm, g.eps)
        hw = embed if head is None else head
        logits = (xh @ hw.T).astype(jnp.float32)             # (B, k1, V)
        if int8:
            return kv_k, kv_v, k_sc, v_sc, logits
        return kv_k, kv_v, logits

    return step


def build_decode_fn(weights, geometry):
    """One batched single-token decode step: the ``k1=1`` slice of
    :func:`build_step_fn` with the historical external signature
    (tokens ``(B,)``, logits ``(B, V)``); int8 geometries insert the
    two scale arrays after the kv buffers."""
    step = build_step_fn(weights, geometry, 1)
    int8 = geometry.quantized

    def decode(kv_k, kv_v, *rest):
        scales, (tokens, positions, block_table) = \
            (rest[:2], rest[2:]) if int8 else ((), rest)
        outs = step(kv_k, kv_v, *scales, tokens[:, None], positions,
                    block_table)
        return outs[:-1] + (outs[-1][:, 0],)

    return decode


def build_verify_fn(weights, geometry):
    """The speculative-decoding signature: ``spec_k + 1`` tokens per
    lane — ``tokens[:, 0]`` is the last accepted token, ``tokens[:,
    1:]`` the drafts — returning logits at every position so the
    scheduler accepts the longest draft prefix the model reproduces."""
    if geometry.spec_k <= 0:
        raise MXNetError("verify needs a geometry with spec_k > 0")
    return build_step_fn(weights, geometry, geometry.spec_k + 1)


def build_prefill_fn(weights, geometry, bucket):
    """Whole-prompt pass for one padded bucket length ``T``.

    ``(kv_k, kv_v, tokens (T,) i32, length () i32,
    block_table (maxp,) i32) -> (kv_k, kv_v, logits (V,) f32)``; int8
    geometries insert ``k_scale``/``v_scale`` after the kv buffers in
    both tuples, exactly as in :func:`build_step_fn`.

    Every position's K/V is scattered into the arena (pad positions land
    on the null page or on this sequence's own not-yet-read slots, both
    harmless: a pad-set page scale is reset by the sequence's own later
    slot-0 write before any masked-in read); the returned logits are the
    last REAL token's — the first generated token comes straight out of
    prefill.  Attention here runs over the in-call full-precision K/V,
    not the arena, so prefill logits are identical between fp32 and int8
    bundles; only the *stored* pages are quantized.
    """
    import jax
    import jax.numpy as jnp

    embed, layers, norm, head = weights
    g = geometry
    H, KV, D, S = g.num_heads, g.num_kv_heads, g.head_dim, g.page_size
    scale = 1.0 / math.sqrt(D)
    t = int(bucket)
    int8 = g.quantized

    def prefill(kv_k, kv_v, *rest):
        if int8:
            k_sc, v_sc, tokens, length, block_table = rest
        else:
            tokens, length, block_table = rest
            k_sc = v_sc = None
        x = embed[tokens]                                    # (T, U)
        pos = jnp.arange(t)
        cos, sin = _rope_tables(pos.astype(jnp.float32), D, g.rope_base)
        cos, sin = cos[:, None, :], sin[:, None, :]          # (T, 1, half)
        pid = block_table[pos // S]                          # (T,)
        slot = pos % S
        causal = (pos[None, :] <= pos[:, None]) \
            & (pos[None, :] < length)                        # (T, T)

        def append(kv, sc, li, rows):
            if not int8:
                return kv.at[li, pid, slot].set(rows), sc
            r32 = rows.astype(jnp.float32)
            amax = jnp.max(jnp.abs(r32), axis=(1, 2))        # (T,)
            # every page start is in-call during prefill: row (p//S)*S
            # fixes page p//S's scale, all rows of a page scatter the
            # same value so duplicate null-page writes stay harmless
            first = amax[(pos // S) * S]
            news = jnp.maximum(first * (_INT8_SCALE_HEADROOM / _INT8_QMAX),
                               _INT8_MIN_SCALE)
            q = jnp.clip(jnp.round(r32 / news[:, None, None]),
                         -_INT8_QMAX, _INT8_QMAX).astype(jnp.int8)
            return kv.at[li, pid, slot].set(q), sc.at[li, pid].set(news)

        for li, lw in enumerate(layers):
            h = _rmsnorm(x, lw["attn_norm"], g.eps)
            q = _rotate((h @ lw["q"].T).reshape(t, H, D), cos, sin)
            k = _rotate((h @ lw["k"].T).reshape(t, KV, D), cos, sin)
            v = (h @ lw["v"].T).reshape(t, KV, D)
            kv_k, k_sc = append(kv_k, k_sc, li, k)
            kv_v, v_sc = append(kv_v, v_sc, li, v)
            # grouped-head attention: queries fold to (T, KV, G, D) so
            # K/V are never replicated H/KV-fold (bitwise-identical to
            # the old jnp.repeat form; head h = kv*G + g ordering)
            qg = q.reshape(t, KV, H // KV, D)
            scores = jnp.einsum("tvgd,uvd->vgtu", qg, k) * scale
            scores = jnp.where(causal[None, None, :, :],
                               scores.astype(jnp.float32), -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            att = jnp.einsum("vgtu,uvd->tvgd", probs, v)
            x = x + att.reshape(t, H * D) @ lw["o"].T
            h2 = _rmsnorm(x, lw["ffn_norm"], g.eps)
            x = x + (jax.nn.silu(h2 @ lw["gate"].T)
                     * (h2 @ lw["up"].T)) @ lw["down"].T
        xh = _rmsnorm(x, norm, g.eps)
        last = jnp.take(xh, length - 1, axis=0)              # (U,)
        hw = embed if head is None else head
        logits = (last @ hw.T).astype(jnp.float32)
        if int8:
            return kv_k, kv_v, k_sc, v_sc, logits
        return kv_k, kv_v, logits

    return prefill


def _donate_kv():
    """Should the serving executables donate the KV buffers (args 0, 1)?

    ``MXNET_SERVE_AOT_DONATE`` = ``1`` forces on, ``0`` forces off,
    unset/``auto`` donates everywhere EXCEPT the CPU backend.  On CPU
    (jax 0.4.37) an executable that carries input-output aliasing does
    not survive ``serialize_executable`` → ``deserialize_and_load``:
    the reloaded binary's aliasing metadata is wrong and every run
    corrupts the allocator heap — results stay correct but the process
    dies with ``corrupted double-linked list`` / SIGSEGV at teardown
    (~50% of runs; bisected fresh-vs-deserialized × donate-vs-not, only
    the deserialized+donated cell fails).  Donation-free decode costs
    one KV-arena copy per step, which CPU serving (tests, smoke CI)
    can afford; accelerator backends keep the zero-copy path.
    """
    mode = os.environ.get("MXNET_SERVE_AOT_DONATE", "auto").lower()
    if mode in ("1", "true"):
        return True
    if mode in ("0", "false"):
        return False
    import jax

    return jax.default_backend() != "cpu"


def _aot_compile(fn, avals, n_state=2):
    """jit → lower → compile; the first ``n_state`` args (KV buffers,
    plus the two scale arrays for int8) donated when the backend
    supports aliasing across serialization (_donate_kv)."""
    import jax

    kwargs = {"donate_argnums": tuple(range(n_state))} \
        if _donate_kv() else {}
    return jax.jit(fn, **kwargs).lower(*avals).compile()


def compile_serving_executables(net, geometry):
    """Build + AOT-compile the decode, verify (when ``spec_k > 0``) and
    per-bucket prefill graphs.

    Returns ``{name: jax.stages.Compiled}`` with weights baked in as
    constants — the bundle is self-contained, no .params sidecar.
    """
    import jax

    g = geometry
    raw = extract_weights(net)
    from ..telemetry import memdump as _memdump

    def dev(a):
        buf = jax.device_put(np.asarray(a, dtype=g.dtype))
        _memdump.tag(buf, origin="param", label="serving_weight")
        return buf
    weights = (dev(raw[0]), [{k: dev(v) for k, v in lw.items()}
                             for lw in raw[1]], dev(raw[2]),
               None if raw[3] is None else dev(raw[3]))
    kv = jax.ShapeDtypeStruct(g.kv_shape(), np.dtype(g.kv_dtype))
    i32 = np.dtype(np.int32)
    sc = jax.ShapeDtypeStruct(g.scale_shape(), np.dtype(np.float32))
    state = (kv, kv, sc, sc) if g.quantized else (kv, kv)
    exes = {}

    def lane_avals(tok_shape):
        return state + (
            jax.ShapeDtypeStruct(tok_shape, i32),
            jax.ShapeDtypeStruct((g.max_batch,), i32),
            jax.ShapeDtypeStruct((g.max_batch, g.max_pages_per_seq), i32))

    exes["decode"] = _aot_compile(build_decode_fn(weights, g),
                                  lane_avals((g.max_batch,)),
                                  n_state=len(state))
    if g.spec_k > 0:
        exes["verify"] = _aot_compile(
            build_verify_fn(weights, g),
            lane_avals((g.max_batch, g.spec_k + 1)), n_state=len(state))
    if g.prefill_chunk > 0:
        # mid-sequence chunked prefill: the step graph at
        # k1=prefill_chunk — scatters a chunk of prompt tokens into the
        # arena and attends causally over arena context, so a prompt
        # resumes at any position (cached-prefix splice, chunk N of M)
        exes["chunk"] = _aot_compile(
            build_step_fn(weights, g, g.prefill_chunk),
            lane_avals((g.max_batch, g.prefill_chunk)),
            n_state=len(state))
    for b in g.prefill_buckets:
        pf_avals = state + (jax.ShapeDtypeStruct((b,), i32),
                            jax.ShapeDtypeStruct((), i32),
                            jax.ShapeDtypeStruct((g.max_pages_per_seq,),
                                                 i32))
        exes["prefill_%d" % b] = _aot_compile(
            build_prefill_fn(weights, g, b), pf_avals, n_state=len(state))
    return exes


def export_serving_bundle(net, path, page_size=None, num_pages=None,
                          max_batch=None, prefill_buckets=None,
                          max_pages_per_seq=None, mesh=None,
                          kv_dtype=None, spec_k=None, paged_kernel=None,
                          prefill_chunk=None):
    """Export ``net`` as a self-contained MXAOT1 serving bundle.

    The bundle carries the AOT-compiled decode + per-bucket prefill
    executables (weights baked in) and the :class:`KVGeometry` in its
    meta, so ``serve.LlamaServer(path)`` starts with zero live compiles.
    Paging knobs default from ``MXNET_SERVE_*`` (docs/env_vars.md);
    ``kv_dtype="int8"`` quantizes the arena pages, ``spec_k=K`` adds the
    compiled ``verify`` executable for n-gram speculative decoding, and
    ``paged_kernel`` ("auto"/"1"/"0", default from
    ``MXNET_SERVE_PAGED_KERNEL``) picks the decode/verify attention the
    executables are built with — the choice is baked into the compiled
    graphs and recorded in the geometry meta.  Returns the geometry.

    ``mesh`` (a Mesh / axes dict — abstract, no devices needed) runs the
    auto-sharding planner over the weight tree and stores its decision
    under ``meta["planner"]`` — chosen per-weight specs plus a suggested
    KV-arena spec — so a sharded server can be brought up from the
    bundle with zero live jits AND zero hand-written specs
    (``planner.plan_serving``).  The executables themselves stay
    single-device; the planner meta is advisory placement data.
    """
    from .. import compile_cache as _ccache

    g = geometry_from_net(net, page_size=page_size, num_pages=num_pages,
                          max_batch=max_batch,
                          prefill_buckets=prefill_buckets,
                          max_pages_per_seq=max_pages_per_seq,
                          kv_dtype=kv_dtype, spec_k=spec_k,
                          paged_kernel=paged_kernel,
                          prefill_chunk=prefill_chunk)
    meta = {"kind": BUNDLE_KIND, "geometry": g.to_dict()}
    if mesh is not None:
        from .. import planner as _planner

        meta["planner"] = _planner.plan_serving(net, g, mesh)
    exes = compile_serving_executables(net, g)
    entries = {name: _ccache.serialize_compiled(c)
               for name, c in exes.items()}
    _ccache.save_bundle(path, entries, meta=meta)
    return g


def read_bundle_geometry(path):
    """Parse + validate a serving bundle's KV geometry WITHOUT
    deserializing any executable (cheap inspection: Predictor's
    redirect error, doctor tools).  Returns ``(KVGeometry, doc)``."""
    from .. import compile_cache as _ccache

    doc = _ccache.load_bundle(path)
    meta = doc.get("meta", {})
    if meta.get("kind") != BUNDLE_KIND:
        raise MXNetError(
            "%s is not a serving bundle (kind=%r) — export one with "
            "serve.export_serving_bundle(net, path)"
            % (path, meta.get("kind")))
    return KVGeometry.from_dict(meta.get("geometry", {}), origin=path), doc


def load_serving_executables(path, expect=None):
    """Load a serving bundle: ``(KVGeometry, {name: Compiled})``.

    Validation happens HERE, not on the first decode: the bundle must be
    a serving bundle, its meta must carry a complete geometry, every
    executable named by the geometry must be present, and — when the
    caller passes ``expect`` (a KVGeometry or partial dict) — the
    KV-page geometry must agree field by field, each mismatch named in
    the error.
    """
    from .. import compile_cache as _ccache

    g, doc = read_bundle_geometry(path)
    if expect is not None:
        check_geometry(g, expect, origin=path)
    want = ["decode"] + ["prefill_%d" % b for b in g.prefill_buckets]
    if g.spec_k > 0:
        want.append("verify")
    if g.prefill_chunk > 0:
        want.append("chunk")
    entries = doc.get("entries", {})
    missing = [n for n in want if n not in entries]
    if missing:
        raise MXNetError("%s: serving bundle is missing executables %s "
                         "for geometry [%s]"
                         % (path, missing, g.describe()))
    exes = {n: _ccache.deserialize_compiled(entries[n]) for n in want}
    return g, exes


def check_geometry(got, expect, origin="bundle"):
    """Field-by-field KV geometry comparison with a clear error.

    ``expect``: KVGeometry or a dict of the subset to pin (e.g.
    ``{"page_size": 16, "dtype": "float32"}``).
    """
    exp = expect.to_dict() if isinstance(expect, KVGeometry) else dict(expect)
    gd = got.to_dict()
    bad = []
    for field, want in exp.items():
        if field not in gd:
            raise MXNetError("%s: unknown geometry field %r" % (origin,
                                                                field))
        have = gd[field]
        if field == "prefill_buckets":
            want = list(want)
        if have != want:
            bad.append("%s: bundle has %r, caller expects %r"
                       % (field, have, want))
    if bad:
        raise MXNetError(
            "%s: KV-page geometry mismatch — refusing to serve (this "
            "would fail inside XLA on the first decode):\n  %s"
            % (origin, "\n  ".join(bad)))
