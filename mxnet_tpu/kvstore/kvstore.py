"""KVStore implementations.

Reference: ``src/kvstore/`` — ``KVStoreLocal`` (+ ``Comm`` reduce hierarchy,
``comm.h:103,451``), ``KVStoreNCCL``, ``KVStoreDist`` over ps-lite.

TPU-native: on one host, "devices" are mesh shards of a single logical array,
so local/device aggregation is an XLA ``add_n`` (and, when values are sharded
jax Arrays, the sum lowers to an ICI all-reduce automatically — the
``CommDevice``/NCCL role).  Multi-host (``dist_*``) rides
``jax.distributed`` + DCN collectives; see ``mxnet_tpu.parallel``.  The
string-dispatch factory mirrors ``KVStore::Create`` (``kvstore.cc:40-77``).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..telemetry import metrics as _metrics
from .base import KVStoreBase, create_via_registry


def _as_list(v):
    return v if isinstance(v, (list, tuple)) else [v]


@KVStoreBase.register
class KVStore(KVStoreBase):
    """Single-process store: 'local' and 'device' modes.

    Parity: ``KVStoreLocal`` (``src/kvstore/kvstore_local.h:69``).  Values
    pushed from multiple "devices" are reduced by summation; ``device`` mode
    differs from ``local`` only in *where* the reference reduced (GPU vs
    CPU) — on TPU the sum runs wherever the buffers live, so both modes
    share one implementation.
    """

    def __init__(self, name="local"):
        self._type = name
        self._store = {}
        self._updater = None
        self._optimizer = None

    @staticmethod
    def is_capable(capability):
        return capability in (KVStoreBase.OPTIMIZER,)

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    size = num_workers

    # ------------------------------------------------------------------
    def init(self, key, value):
        keys = _as_list(key)
        values = _as_list(value)
        if len(keys) != len(values):
            values = [value] * len(keys)
        for k, v in zip(keys, values):
            self._store[str(k)] = v.copy() if isinstance(v, NDArray) else \
                NDArray(v)

    def _reduce(self, values):
        """Sum pushed buffers, wherever they live.

        Device mode's pushes can arrive committed to DIFFERENT devices
        (one per data-parallel worker); XLA refuses cross-device adds, so
        every operand is first brought to the first buffer's device —
        the reference's CommDevice gathers to a reduction root the same
        way (comm.h:451) before summing.  PJRT overlaps the transfers.
        """
        import jax

        vals = _as_list(values)
        acc = vals[0].data()
        home = getattr(acc, "device", None)
        for v in vals[1:]:
            d = v.data()
            if home is not None and getattr(d, "device", None) != home:
                d = jax.device_put(d, home)
            acc = acc + d
        return acc

    @staticmethod
    def _reduce_sparse(values):
        """Merge row_sparse pushes: concat (idx, vals) pairs, sum dupes.

        Parity: CommCPU's row_sparse reduce (src/kvstore/comm.h) — the
        aggregated gradient stays sparse all the way to the updater.
        Cross-device pushes are gathered to the first buffer's device
        first (same root-gather as the dense _reduce).
        """
        import jax

        vals = _as_list(values)
        home = getattr(vals[0].values.data(), "device", None)

        def rehome(rs):
            """A copy on the reduction root; the caller's buffers stay
            on their own device (matching the dense _reduce)."""
            if home is None or getattr(rs.values.data(), "device",
                                       None) == home:
                return rs
            return type(rs)(
                NDArray(jax.device_put(rs.values.data(), home)),
                NDArray(jax.device_put(rs.indices.data(), home)),
                rs.shape, canonical=rs._canonical)

        acc = vals[0]
        for v in vals[1:]:
            acc = acc + rehome(v)
        return acc.compact()

    def push(self, key, value, priority=0):
        from ..ndarray.sparse import RowSparseNDArray

        keys = _as_list(key)
        _metrics.counter("mxnet_kvstore_push_total",
                         help="keys pushed", store=self._type
                         ).inc(len(keys))
        if len(keys) == 1:
            values = [value]
        else:
            values = value
        for k, v in zip(keys, values):
            k = str(k)
            first = _as_list(v)[0]
            if isinstance(first, RowSparseNDArray):
                agg = self._reduce_sparse(v)
                if self._updater is not None:
                    if k not in self._store:
                        raise MXNetError("key %s not initialized" % k)
                    self._updater(int(k) if k.isdigit() else k,
                                  agg, self._store[k])
                else:
                    # no updater: REPLACE the stored value with the
                    # aggregated push, densified — same semantics as the
                    # dense branch below (reference KVStoreLocal)
                    self._store[k] = NDArray(
                        agg.scatter_add_into(
                            jnp.zeros(agg.shape, agg.dtype)))
                continue
            agg = self._reduce(v)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError("key %s not initialized" % k)
                self._updater(int(k) if k.isdigit() else k,
                              NDArray(agg), self._store[k])
            else:
                self._store[k] = NDArray(agg)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = _as_list(key)
        _metrics.counter("mxnet_kvstore_pull_total",
                         help="keys pulled", store=self._type
                         ).inc(len(keys))
        if len(keys) == 1:
            outs = [out]
        else:
            outs = out
        for k, o in zip(keys, outs):
            k = str(k)
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            src = self._store[k]
            for dst in _as_list(o):
                dst._set_data(src.data().astype(dst.dtype))

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (parity: KVStore.pushpull)."""
        keys = _as_list(key)
        if len(keys) == 1:
            values, outs = [value], [out]
        else:
            values, outs = value, out
        for k, v, o in zip(keys, values, outs):
            agg = self._reduce(v)
            kstr = str(k)
            if self._updater is not None:
                if kstr not in self._store:
                    raise MXNetError("key %s not initialized" % kstr)
                self._updater(int(kstr) if kstr.isdigit() else kstr,
                              NDArray(agg), self._store[kstr])
                agg = self._store[kstr].data()
            if o is not None:
                for dst in _as_list(o):
                    dst._set_data(agg.astype(dst.dtype))

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in ``row_ids`` (parity: kvstore_dist row path)."""
        if row_ids is None:
            return self.pull(key, out, priority)
        k = str(_as_list(key)[0])
        src = self._store[k]
        rows = row_ids.data() if isinstance(row_ids, NDArray) else \
            jnp.asarray(row_ids)
        gathered = src.data()[rows.astype(jnp.int32)]
        for dst in _as_list(out):
            full = jnp.zeros(src.shape, src.dtype).at[
                rows.astype(jnp.int32)].set(gathered)
            dst._set_data(full.astype(dst.dtype))

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer):
        from .. import optimizer as opt_mod

        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        pass

    # -- elastic membership (API parity with DistKVStore) ------------------
    # a single-process store has no membership plane: the roster is this
    # process, forever at epoch 0 — harness code written against the
    # elastic API (set_step/join/resync) runs unchanged on `local`
    def set_step(self, step):
        pass

    def resync(self):
        return {}

    def join(self):
        return {"step": 0, "roster": [self.rank]}


@KVStoreBase.register
class TestStore(KVStoreBase):
    """Minimal reference store used by tests (parity: base.py TestStore)."""

    def __init__(self):
        self._store = {}

    @staticmethod
    def is_capable(capability):
        return False

    @property
    def type(self):
        return "teststore"

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    size = num_workers

    def broadcast(self, key, value, out, priority=0):
        for dst in _as_list(out):
            dst._set_data(_as_list(value)[0].data())

    def pushpull(self, key, value, out=None, priority=0):
        vals = _as_list(value)
        acc = vals[0].data()
        for v in vals[1:]:
            acc = acc + v.data()
        for dst in _as_list(out):
            dst._set_data(acc.astype(dst.dtype))


def create(name="local", **kwargs):
    """String-dispatch factory (parity: KVStore::Create, kvstore.cc:40)."""
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    name = name.lower()
    if name in ("local", "device", "local_allreduce_cpu",
                "local_allreduce_device", "nccl", "tpu"):
        return KVStore("device" if name in ("device", "nccl", "tpu")
                       else "local")
    if name.startswith("dist"):
        from ..parallel.dist_kvstore import DistKVStore

        return DistKVStore(name)
    return create_via_registry(name, **kwargs)
