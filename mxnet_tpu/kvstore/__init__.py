"""KVStore package (parity: python/mxnet/kvstore/)."""
from .base import KVStoreBase  # noqa: F401
from .kvstore import KVStore, TestStore, create  # noqa: F401
from .kvstore_server import KVStoreServer  # noqa: F401
