"""KVStore base interface + registry.

Reference: ``python/mxnet/kvstore/base.py`` — ``KVStoreBase`` with the
``@register`` plugin mechanism (``base.py:75,229-248``) so alternative stores
(test stores, Horovod-style) can be slotted in by name.
"""
from __future__ import annotations

from ..base import MXNetError


class KVStoreBase:
    """Abstract key-value store (parity: kvstore.base.KVStoreBase)."""

    kv_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        KVStoreBase.kv_registry[name] = klass
        return klass

    @staticmethod
    def is_capable(capability):
        raise NotImplementedError

    OPTIMIZER = "optimizer"

    # -- interface ---------------------------------------------------------
    def broadcast(self, key, value, out, priority=0):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    def set_optimizer(self, optimizer):
        raise NotImplementedError

    @property
    def type(self):
        raise NotImplementedError

    @property
    def rank(self):
        raise NotImplementedError

    @property
    def num_workers(self):
        raise NotImplementedError

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise NotImplementedError

    def load_optimizer_states(self, fname):
        raise NotImplementedError


def create_via_registry(name, **kwargs):
    name = name.lower()
    if name not in KVStoreBase.kv_registry:
        raise MXNetError("no kvstore type %r registered" % name)
    return KVStoreBase.kv_registry[name](**kwargs)
