"""Server-process bootstrap (parity: python/mxnet/kvstore/kvstore_server.py:30).

A process launched with ``DMLC_ROLE=server`` calls ``KVStoreServer.run()``
(or just imports mxnet_tpu and calls ``serve_if_server()``, which
tools/launch.py arranges) and blocks serving pushes/pulls until every
distinct worker rank has sent STOP (ps-lite Finalize semantics; the
launcher additionally terminates servers if a worker dies without one).

Preemption: a SIGTERM (the TPU-pod eviction signal) triggers a clean
``DistServer.shutdown()`` — the listener and every connection close, so
workers see a connection error immediately (and retry/fail fast) instead
of waiting out their wire timeout against a half-dead process.
"""
from __future__ import annotations

import os
import signal
import threading


class KVStoreServer:
    def __init__(self, kvstore=None):
        self._server_id = int(os.environ.get("DMLC_SERVER_ID", "0"))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._root_port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        self._sync = "async" not in os.environ.get(
            "MXNET_KVSTORE_MODE", "dist_sync")

    def run(self):
        from ..parallel.dist_kvstore import DistServer, _server_port
        from ..telemetry import metrics as _metrics

        server = DistServer(
            _server_port(self._root_port, self._server_id),
            self._num_workers, sync=self._sync)
        # one-time bootstrap facts: which shard this is and how many
        # workers it expects (MXNET_TELEMETRY_DUMP snapshots from a
        # server process then identify themselves)
        _metrics.gauge("mxnet_kvstore_server_id",
                       help="shard id of this server process"
                       ).set(self._server_id)
        _metrics.gauge("mxnet_kvstore_server_expected_workers",
                       help="worker ranks this server waits for"
                       ).set(self._num_workers)
        # elastic-membership gauges exist from boot (scrapes before the
        # first eviction/join must show epoch 0 + a full roster, not an
        # absent family); DistServer keeps them current afterwards
        _metrics.gauge("mxnet_membership_epoch",
                       help="membership epoch of this kvstore shard "
                            "(bumps on every eviction or admission)"
                       ).set(0)
        _metrics.gauge("mxnet_ranks_active",
                       help="worker ranks currently in the membership "
                            "roster").set(self._num_workers)
        if threading.current_thread() is threading.main_thread():
            prev = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                server.shutdown()
                if callable(prev):
                    prev(signum, frame)

            signal.signal(signal.SIGTERM, _on_term)
        server.run()


def serve_if_server():
    """If this process is a server/scheduler, serve forever then exit.

    The scheduler role of ps-lite collapsed into the servers (workers
    rendezvous directly on server addresses), so a ``scheduler`` process
    is a no-op kept for launcher compatibility.
    """
    role = os.environ.get("DMLC_ROLE", "worker")
    if role == "server":
        KVStoreServer().run()
        raise SystemExit(0)
    if role == "scheduler":
        raise SystemExit(0)
