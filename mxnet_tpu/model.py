"""Checkpoint helpers + training-callback params.

Capability parity: ``python/mxnet/model.py`` (``save_checkpoint:407``,
``load_checkpoint:456``, ``BatchEndParam:80``).  Storage: ``nd.save``
writes the reference's byte-level ``.params`` binary (magic-number
format, ``ndarray/legacy_io.py``) so checkpoints interchange with the
reference; the symbol file is the same JSON idea.
"""
from __future__ import annotations

from collections import namedtuple

from . import ndarray as nd
from .base import MXNetError

BatchEndParam = namedtuple(
    "BatchEndParam", ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Checkpoint symbol + parameters to ``prefix-symbol.json`` and
    ``prefix-%04d.params``.

    Both writes are atomic (tmp + ``os.replace`` via ``base.atomic_path``):
    a preemption mid-checkpoint leaves the previous epoch's files intact
    and loadable (docs/fault_tolerance.md).
    """
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    return param_name


def load_checkpoint(prefix, epoch):
    """Returns (symbol, arg_params, aux_params) saved by save_checkpoint."""
    from . import symbol as sym

    try:
        symbol = sym.load("%s-symbol.json" % prefix)
    except FileNotFoundError:
        symbol = None
    loaded = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            raise MXNetError("invalid param file entry %r" % k)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy estimator-style Model API (parity: model.py:486
    FeedForward — deprecated in the reference in favor of Module, kept
    for API completeness).  Internally a thin driver over
    ``mx.module.Module``: one compiled train step per shape, sklearn-ish
    ``fit``/``predict``/``score``/``save``/``load``.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None,
                 epoch_size=None, optimizer="sgd",
                 initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, begin_epoch=0,
                 **kwargs):
        from . import initializer as init_mod

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        if epoch_size is not None:
            import warnings

            warnings.warn(
                "FeedForward: epoch_size is accepted for API parity but "
                "not used — epochs are bounded by the iterator; wrap an "
                "infinite iterator (e.g. mx.io.ResizeIter) instead")
        self.optimizer = optimizer
        self.initializer = initializer or init_mod.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self._kwargs = kwargs
        self._module = None

    # -- data plumbing -----------------------------------------------------
    def _init_iter(self, X, y, is_train):
        import numpy as _np

        from .io.io import DataIter, NDArrayIter

        if isinstance(X, DataIter):
            return X
        X = _np.asarray(X)
        if y is None and is_train:
            raise MXNetError("y is required for training")
        batch = min(self.numpy_batch_size, X.shape[0])
        return NDArrayIter(
            X, None if y is None else _np.asarray(y),
            batch_size=batch, shuffle=is_train,
            last_batch_handle="roll_over" if is_train else "pad")

    def _build_module(self, data_iter):
        from .module.module import Module

        label_names = tuple(n for n, _ in
                            (data_iter.provide_label or ()))
        if not label_names:
            # predict-mode iterators carry no labels, but the symbol's
            # label variables must still be classed as labels (NOT
            # parameters), or set_params would demand values for them
            label_names = tuple(n for n in self.symbol.list_arguments()
                                if n.endswith("_label"))
        self._module = Module(
            self.symbol, data_names=tuple(
                n for n, _ in data_iter.provide_data),
            label_names=label_names, context=self.ctx)
        return self._module

    def _ensure_bound(self, data_iter, need_labels):
        """(Re)bind the inner Module for inference; a module built
        without labels cannot score, so label requirements force a
        rebuild (otherwise the metric would silently never update)."""
        # _module_bound_with_labels tracks the BIND-time label
        # topology: a module bound without label shapes cannot score
        # (the metric would silently never update), and vice versa for
        # label-less forwards — mismatches force a rebuild.  NOTE:
        # alternating predict()/score() therefore re-binds each flip
        # (XLA's persistent compilation cache absorbs the recompile);
        # batch eval loops should score() with a metric instead of
        # interleaving
        if self._module is None or not self._module.binded or \
                need_labels != getattr(self, "_module_bound_with_labels",
                                       None):
            mod = self._build_module(data_iter)
            mod.bind(data_shapes=data_iter.provide_data,
                     label_shapes=data_iter.provide_label
                     if need_labels else None,
                     for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=False)
            self._module_bound_with_labels = need_labels
        return self._module

    # -- estimator API -----------------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None,
            work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        """Train (parity: model.py:827 FeedForward.fit)."""
        train_data = self._init_iter(X, y, is_train=True)
        mod = self._build_module(train_data)
        mod.fit(train_data,
                eval_data=None if eval_data is None
                else self._init_iter(
                    eval_data[0] if isinstance(eval_data, tuple)
                    else eval_data,
                    eval_data[1] if isinstance(eval_data, tuple)
                    else None, is_train=False),
                eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback,
                kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=self._kwargs
                or (("learning_rate", 0.01),),
                initializer=self.initializer,
                arg_params=self.arg_params,
                aux_params=self.aux_params,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback,
                monitor=monitor)
        self.arg_params, self.aux_params = mod.get_params()
        self._module_bound_with_labels = True
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """Forward over a dataset (parity: model.py:707): single-output
        symbols return one array, multi-output a list — delegates to
        ``BaseModule.predict`` (pad slicing, batch merging)."""
        import numpy as _np

        data_iter = self._init_iter(X, None, is_train=False)
        mod = self._ensure_bound(data_iter, need_labels=False)
        outs = mod.predict(data_iter, num_batch=num_batch, reset=reset)
        if isinstance(outs, (list, tuple)):
            return [_np.asarray(o.asnumpy()) for o in outs]
        return _np.asarray(outs.asnumpy())

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        """Evaluate a metric over a dataset (parity: model.py:776) —
        delegates to ``BaseModule.score`` (per-batch callbacks
        included)."""
        data_iter = self._init_iter(
            X[0] if isinstance(X, tuple) else X,
            X[1] if isinstance(X, tuple) else None, is_train=False)
        mod = self._ensure_bound(data_iter, need_labels=True)
        res = mod.score(data_iter, eval_metric, num_batch=num_batch,
                        batch_end_callback=batch_end_callback,
                        reset=reset)
        return res[0][1]

    # -- persistence -------------------------------------------------------
    def save(self, prefix, epoch=None, remove_amp_cast=True):
        """Checkpoint (parity: model.py:931)."""
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol,
                        self.arg_params or {}, self.aux_params or {},
                        remove_amp_cast=remove_amp_cast)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """Restore a saved FeedForward (parity: model.py:956)."""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None,
               epoch_size=None, optimizer="sgd", initializer=None,
               eval_data=None, eval_metric="acc",
               epoch_end_callback=None, batch_end_callback=None,
               kvstore="local", logger=None, work_load_list=None,
               eval_end_callback=None, eval_batch_end_callback=None,
               **kwargs):
        """Build + fit in one call (parity: model.py:987)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback,
                  kvstore=kvstore, logger=logger,
                  work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
