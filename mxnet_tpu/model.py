"""Checkpoint helpers + training-callback params.

Capability parity: ``python/mxnet/model.py`` (``save_checkpoint:407``,
``load_checkpoint:456``, ``BatchEndParam:80``).  Storage: ``nd.save``
writes the reference's byte-level ``.params`` binary (magic-number
format, ``ndarray/legacy_io.py``) so checkpoints interchange with the
reference; the symbol file is the same JSON idea.
"""
from __future__ import annotations

from collections import namedtuple

from . import ndarray as nd
from .base import MXNetError

BatchEndParam = namedtuple(
    "BatchEndParam", ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Checkpoint symbol + parameters to ``prefix-symbol.json`` and
    ``prefix-%04d.params``."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    return param_name


def load_checkpoint(prefix, epoch):
    """Returns (symbol, arg_params, aux_params) saved by save_checkpoint."""
    from . import symbol as sym

    try:
        symbol = sym.load("%s-symbol.json" % prefix)
    except FileNotFoundError:
        symbol = None
    loaded = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            raise MXNetError("invalid param file entry %r" % k)
    return symbol, arg_params, aux_params
