"""Logging utilities (parity: python/mxnet/log.py).

``get_logger`` returns a configured logger with the reference's level
coloring when writing to a TTY.
"""
from __future__ import annotations

import logging
import sys

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET

PY3 = True


class _Formatter(logging.Formatter):
    """Colored level labels on TTYs (parity: log.py _Formatter)."""

    def __init__(self, colored=True):
        self._colored = colored
        super().__init__(datefmt="%m%d %H:%M:%S")

    def _get_color(self, level):
        if level >= ERROR:
            return "\x1b[31m"
        if level >= WARNING:
            return "\x1b[33m"
        return "\x1b[32m"

    def _get_label(self, level):
        if level == INFO:
            return "I"
        if level == WARNING:
            return "W"
        if level == ERROR:
            return "E"
        if level == CRITICAL:
            return "C"
        return "U"

    def format(self, record):
        if self._colored:
            fmt = (self._get_color(record.levelno)
                   + self._get_label(record.levelno)
                   + "%(asctime)s %(process)d %(pathname)s:%(funcName)s"
                   ":%(lineno)d\x1b[0m %(message)s")
        else:
            fmt = (self._get_label(record.levelno)
                   + "%(asctime)s %(process)d %(pathname)s:%(funcName)s"
                   ":%(lineno)d %(message)s")
        self._style._fmt = fmt
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Configured logger (parity: log.py:90 get_logger)."""
    logger = logging.getLogger(name)
    if name is not None and not getattr(logger, "_init_done", None):
        logger._init_done = True
        if filename:
            mode = filemode if filemode else "a"
            hdlr = logging.FileHandler(filename, mode)
            colored = False
        else:
            hdlr = logging.StreamHandler(sys.stderr)
            colored = hasattr(sys.stderr, "isatty") and sys.stderr.isatty()
        hdlr.setFormatter(_Formatter(colored))
        logger.addHandler(hdlr)
        logger.setLevel(level)
    return logger


# reference exports the camelCase alias too
getLogger = get_logger
