"""Learning-rate schedulers (parity: python/mxnet/lr_scheduler.py)."""
from __future__ import annotations

import math

from .base import MXNetError


class LRScheduler:
    """Base scheduler: maps num_update -> learning rate."""

    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0,
                 warmup_mode="linear"):
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode
        if warmup_begin_lr > base_lr:
            raise MXNetError("base lr must be larger than warmup_begin_lr")
        if warmup_steps < 0:
            raise MXNetError("warmup_steps must be >= 0")

    def get_warmup_lr(self, num_update):
        assert num_update < self.warmup_steps
        if self.warmup_mode == "linear":
            increase = (self.warmup_final_lr - self.warmup_begin_lr) \
                * num_update / self.warmup_steps
            return self.warmup_begin_lr + increase
        if self.warmup_mode == "constant":
            return self.warmup_begin_lr
        raise MXNetError("invalid warmup_mode %r" % self.warmup_mode)

    def _traced_warmup(self, t):
        import jax.numpy as jnp

        if self.warmup_mode == "constant" or self.warmup_steps == 0:
            return jnp.float32(self.warmup_begin_lr)
        return jnp.float32(self.warmup_begin_lr) + (
            (self.warmup_final_lr - self.warmup_begin_lr)
            * t.astype(jnp.float32) / self.warmup_steps)

    def _with_warmup(self, t, lr):
        import jax.numpy as jnp

        if self.warmup_steps <= 0:
            return lr
        return jnp.where(t < self.warmup_steps, self._traced_warmup(t), lr)

    def traced(self, t):
        """lr as a pure jnp function of a TRACED update count.

        The device-side n-step training loop (``JitTrainStep.step_n``)
        evaluates the schedule inside ``lax.fori_loop`` — every update sees
        its scheduled lr without per-step host dispatch.  Subclasses without
        a pure form return None and step_n falls back to per-step dispatch.
        """
        return None

    _anchor = None

    def _ensure_anchor(self):
        # the pre-decay base lr for stateful schedulers: captured at first
        # use, AFTER the optimizer has adopted its learning_rate into
        # base_lr (reference semantics: the eager path decays base_lr in
        # place)
        if self._anchor is None:
            self._anchor = self.base_lr

    def __call__(self, num_update):  # pragma: no cover - abstract
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr *= factor every ``step`` updates (parity: FactorScheduler)."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8, base_lr=0.01,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if step < 1:
            raise MXNetError("schedule step must be greater or equal than 1")
        if factor > 1.0:
            raise MXNetError("factor must be no more than 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        self._ensure_anchor()
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
        return self.base_lr

    def traced(self, t):
        import jax.numpy as jnp

        self._ensure_anchor()
        k = jnp.maximum(0, (t - 1) // self.step)
        lr = self._anchor * jnp.float32(self.factor) ** k
        return self._with_warmup(t, jnp.maximum(lr, self.stop_factor_lr))


class MultiFactorScheduler(LRScheduler):
    """lr *= factor at given steps (parity: MultiFactorScheduler)."""

    def __init__(self, step, factor=1, base_lr=0.01, warmup_steps=0,
                 warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        assert isinstance(step, list) and len(step) >= 1
        for i, _step in enumerate(step):
            if i != 0 and step[i] <= step[i - 1]:
                raise MXNetError("schedule step must be an increasing list")
            if _step < 1:
                raise MXNetError("schedule step must be greater than 1")
        self.step = step
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def traced(self, t):
        import jax.numpy as jnp

        self._ensure_anchor()
        k = jnp.sum(t > jnp.asarray(self.step, jnp.int32))
        lr = self._anchor * jnp.float32(self.factor) ** k
        return self._with_warmup(t, lr)

    def __call__(self, num_update):
        self._ensure_anchor()
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        while self.cur_step_ind <= len(self.step) - 1:
            if num_update > self.step[self.cur_step_ind]:
                self.count = self.step[self.cur_step_ind]
                self.cur_step_ind += 1
                self.base_lr *= self.factor
            else:
                return self.base_lr
        return self.base_lr


class PolyScheduler(LRScheduler):
    """Polynomial decay to final_lr over max_update steps."""

    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        assert isinstance(max_update, int)
        if max_update < 1:
            raise MXNetError("maximum number of updates must be strictly positive")
        self.power = pwr
        self.base_lr_orig = self.base_lr
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = self.max_update - self.warmup_steps

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update <= self.max_update:
            self.base_lr = self.final_lr + (self.base_lr_orig - self.final_lr) \
                * pow(1 - float(num_update - self.warmup_steps) /
                      float(self.max_steps), self.power)
        return self.base_lr

    def traced(self, t):
        import jax.numpy as jnp

        tt = jnp.minimum(t, self.max_update).astype(jnp.float32)
        frac = 1.0 - (tt - self.warmup_steps) / float(self.max_steps)
        lr = self.final_lr + (self.base_lr_orig - self.final_lr) \
            * frac ** self.power
        return self._with_warmup(t, lr)


class CosineScheduler(LRScheduler):
    """Cosine decay (parity: CosineScheduler)."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0, warmup_steps=0,
                 warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        assert isinstance(max_update, int)
        if max_update < 1:
            raise MXNetError("maximum number of updates must be strictly positive")
        self.base_lr_orig = base_lr
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = self.max_update - self.warmup_steps

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update <= self.max_update:
            self.base_lr = self.final_lr + (self.base_lr_orig - self.final_lr) \
                * (1 + math.cos(
                    math.pi * (num_update - self.warmup_steps)
                    / self.max_steps)) / 2
        return self.base_lr

    def traced(self, t):
        import jax.numpy as jnp

        tt = jnp.minimum(t, self.max_update).astype(jnp.float32)
        lr = self.final_lr + (self.base_lr_orig - self.final_lr) * (
            1.0 + jnp.cos(jnp.pi * (tt - self.warmup_steps)
                          / float(self.max_steps))) / 2.0
        return self._with_warmup(t, lr)
