"""Network visualization (parity: ``python/mxnet/visualization.py``).

``print_summary`` — layer-by-layer table with output shapes and parameter
counts; ``plot_network`` — graphviz DOT rendering when graphviz is
importable (gated, not required).
"""
from __future__ import annotations

import json

from .base import MXNetError


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Prints a summary table of the symbol's nodes.

    Parameters
    ----------
    symbol : Symbol
    shape : dict of input name -> shape, for output-shape inference
    """
    if positions is None:
        positions = [.44, .64, .74, 1.]
    show_shape = shape is not None
    internals = symbol.get_internals()
    if show_shape:
        _, out_shapes, _ = internals.infer_shape_partial(**shape)
        if out_shapes is None:
            raise MXNetError("Input shape is incomplete")
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ['Layer (type)', 'Output Shape', 'Param #',
                  'Previous Layer']

    def print_row(fields, positions):
        line = ''
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += ' ' * (positions[i] - len(line))
        print(line)

    print('_' * line_length)
    print_row(to_display, positions)
    print('=' * line_length)

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        pre_filter = 0
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in heads:
                    pre_node.append(input_name)
                    if show_shape:
                        key = input_name
                        if input_node["op"] != "null":
                            key += "_output"
                        if key in shape_dict:
                            shape = shape_dict[key][1:]
                            pre_filter = pre_filter + int(shape[0]) \
                                if shape else pre_filter
        cur_param = 0
        attrs = node.get("attrs", {})
        if op == 'Convolution':
            num_filter = int(attrs.get("num_filter", 0))
            kernel = attrs.get("kernel", "()")
            if isinstance(kernel, str):
                kernel = eval(kernel)  # attr round-trips as str or list
            k = 1
            for dim in kernel:
                k *= int(dim)
            cur_param = pre_filter * num_filter * k
            if attrs.get("no_bias") not in ('True', True, 'true'):
                cur_param += num_filter
        elif op == 'FullyConnected':
            num_hidden = int(attrs.get("num_hidden", 0))
            cur_param = pre_filter * num_hidden
            if attrs.get("no_bias") not in ('True', True, 'true'):
                cur_param += num_hidden
        elif op == 'BatchNorm':
            cur_param = pre_filter * 4
        elif op == 'Embedding':
            cur_param = (int(attrs.get("input_dim", 0)) *
                         int(attrs.get("output_dim", 0)))
        first_connection = pre_node[0] if pre_node else ''
        fields = [node['name'] + '(' + op + ')',
                  "x".join(str(x) for x in out_shape),
                  cur_param, first_connection]
        print_row(fields, positions)
        for i in range(1, len(pre_node)):
            fields = ['', '', '', pre_node[i]]
            print_row(fields, positions)
        return cur_param

    total_params = 0
    heads = set(conf["arg_nodes"])
    for i, node in enumerate(nodes):
        out_shape = []
        op = node["op"]
        if op == "null" and i > 0:
            continue
        if op != "null" or i in heads:
            if show_shape:
                key = node["name"] + ("_output" if op != "null" else "")
                if key in shape_dict:
                    out_shape = shape_dict[key][1:]
        total_params += print_layer_summary(node, out_shape)
        if i == len(nodes) - 1:
            print('=' * line_length)
        else:
            print('_' * line_length)
    print("Total params: {params}".format(params=total_params))
    print('_' * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Creates a graphviz Digraph of the symbol (requires graphviz)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires the graphviz package")
    node_attrs = node_attrs or {}
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    node_attr = {"shape": "box", "fixedsize": "true",
                 "width": "1.3", "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    hidden_nodes = set()
    for node in nodes:
        op = node["op"]
        name = node["name"]
        if op == "null":
            if name.endswith(("_weight", "_bias", "_gamma", "_beta",
                              "_moving_mean", "_moving_var",
                              "_running_mean", "_running_var")) \
                    and hide_weights:
                hidden_nodes.add(name)
                continue
            dot.node(name=name, label=name, fillcolor="#8dd3c7",
                     **node_attr)
        else:
            dot.node(name=name, label="%s\n%s" % (op, name),
                     fillcolor="#fb8072", **node_attr)
    for node in nodes:
        if node["op"] == "null":
            continue
        for item in node["inputs"]:
            input_node = nodes[item[0]]
            if input_node["name"] not in hidden_nodes:
                dot.edge(tail_name=input_node["name"],
                         head_name=node["name"])
    return dot
