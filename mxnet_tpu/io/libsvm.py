"""LibSVM text-format reader + batch iterator.

Parity: ``src/io/iter_libsvm.cc`` (LibSVMIter with ``data_libsvm``,
``data_shape``, optional ``label_libsvm``, ``num_parts``/``part_index``
sharding) feeding ``example/sparse/linear_classification.py``.

The wire format is plain text, one example per line::

    <label>[,<label>...] <index>:<value> <index>:<value> ...

Indices are zero-based (the reference's documented contract).  Batches
come out as ``CSRNDArray`` data — the row slice is taken host-side on
the stored numpy CSR triplet (IO is host work; the device only sees the
batch), so step cost scales with nnz per batch, not the corpus.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.sparse import CSRNDArray
from .io import DataBatch, DataDesc, DataIter


def read_libsvm(path, num_features=None, label_width=1):
    """Parse a libsvm file → ``(data, indices, indptr, labels)`` numpy
    CSR triplet + ``(n, label_width)`` label array."""
    vals, cols, indptr, labels = [], [], [0], []
    max_col = -1
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            # labels: first token(s) with no ':' — the reference packs
            # label_width labels comma- or space-separated at the front
            head = parts[0]
            feats_start = 1
            if "," in head:
                lab = [float(t) for t in head.split(",")]
            else:
                lab = [float(head)]
                while len(lab) < label_width and feats_start < len(parts) \
                        and ":" not in parts[feats_start]:
                    lab.append(float(parts[feats_start]))
                    feats_start += 1
            if len(lab) != label_width:
                raise MXNetError(
                    "libsvm %s:%d: %d labels (want %d)"
                    % (path, lineno, len(lab), label_width))
            labels.append(lab)
            for tok in parts[feats_start:]:
                try:
                    idx_s, val_s = tok.split(":", 1)
                    idx = int(idx_s)
                    val = float(val_s)
                except ValueError:
                    raise MXNetError("libsvm %s:%d: bad token %r"
                                     % (path, lineno, tok))
                if idx < 0:
                    raise MXNetError(
                        "libsvm %s:%d: negative feature index %d "
                        "(indices are ZERO-based)" % (path, lineno, idx))
                cols.append(idx)
                vals.append(val)
                max_col = max(max_col, idx)
            indptr.append(len(cols))
    if num_features is not None and max_col >= num_features:
        raise MXNetError(
            "libsvm %s: feature index %d out of range for data_shape "
            "width %d (indices are ZERO-based)" % (path, max_col,
                                                   num_features))
    return (np.asarray(vals, np.float32), np.asarray(cols, np.int64),
            np.asarray(indptr, np.int64),
            np.asarray(labels, np.float32))


class LibSVMIter(DataIter):
    """Batch iterator over libsvm files (parity: ``io.LibSVMIter``).

    ``data_shape`` is the per-example feature width ``(D,)``; data
    batches are ``CSRNDArray`` of shape ``(batch_size, D)``.  Labels
    come from the libsvm label column, or from a second
    ``label_libsvm`` file when the labels are themselves sparse/wide.
    ``num_parts``/``part_index`` shard the example stream for
    distributed training (contiguous split, like the reference's
    InputSplit).
    """

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, label_shape=None, num_parts=1,
                 part_index=0, round_batch=True, **kwargs):
        super().__init__(batch_size)
        if len(tuple(data_shape)) != 1:
            raise MXNetError("LibSVMIter: data_shape must be (D,)")
        self._dim = int(tuple(data_shape)[0])
        label_width = int(np.prod(label_shape)) if label_shape else 1
        vals, cols, indptr, labels = read_libsvm(
            data_libsvm, self._dim, label_width=1 if label_libsvm
            else label_width)
        if label_libsvm is not None:
            lw = label_width
            lvals, lcols, lindptr, _ = read_libsvm(label_libsvm)
            n = len(lindptr) - 1
            dense_lab = np.zeros((n, lw), np.float32)
            for r in range(n):
                sl = slice(lindptr[r], lindptr[r + 1])
                dense_lab[r, lcols[sl].astype(np.int64)] = lvals[sl]
            labels = dense_lab
        n_total = len(indptr) - 1
        if labels.shape[0] != n_total:
            raise MXNetError("LibSVMIter: %d examples but %d labels"
                             % (n_total, labels.shape[0]))
        # contiguous shard for this part
        if not (0 <= part_index < num_parts):
            raise MXNetError("LibSVMIter: part_index out of range")
        per = -(-n_total // num_parts)
        lo, hi = part_index * per, min(n_total, (part_index + 1) * per)
        self._vals, self._cols, self._indptr = vals, cols, indptr
        self._labels = labels
        self._lo, self._hi = lo, hi
        self._round = round_batch
        self._label_width = labels.shape[1]
        self._cursor = lo
        self.provide_data = [DataDesc("data", (batch_size, self._dim))]
        self.provide_label = [DataDesc(
            "softmax_label",
            (batch_size,) if self._label_width == 1
            else (batch_size, self._label_width))]

    @property
    def num_examples(self):
        return self._hi - self._lo

    def reset(self):
        self._cursor = self._lo

    def _rows(self, row_ids):
        """CSR slice of the given example rows, host-side."""
        counts = (self._indptr[row_ids + 1]
                  - self._indptr[row_ids]).astype(np.int64)
        new_indptr = np.zeros(len(row_ids) + 1, np.int64)
        np.cumsum(counts, out=new_indptr[1:])
        take = np.concatenate(
            [np.arange(self._indptr[r], self._indptr[r + 1])
             for r in row_ids]) if len(row_ids) else \
            np.zeros((0,), np.int64)
        data = CSRNDArray(self._vals[take], new_indptr, self._cols[take],
                          (len(row_ids), self._dim))
        lab = self._labels[row_ids]
        if self._label_width == 1:
            lab = lab.reshape(-1)
        return data, NDArray(lab)

    def iter_next(self):
        return self._cursor < self._hi

    def next(self):
        if not self.iter_next():
            raise StopIteration
        end = self._cursor + self.batch_size
        ids = np.arange(self._cursor, min(end, self._hi))
        pad = 0
        if end > self._hi:
            pad = end - self._hi
            if len(ids) == 0:
                raise StopIteration
            # ALWAYS emit a full batch_size batch (the DataBatch pad
            # contract: consumers slice off the last `pad` rows, and
            # Module binds to the advertised (batch_size, D) shape).
            # round_batch wraps the filler to the shard's front (the
            # reference's epoch-wrapping semantics); otherwise the
            # filler repeats in-shard rows — either way the filler is
            # modulo-clamped so it can never leave this shard.
            fill_base = self._lo if self._round else ids[0]
            ids = np.concatenate(
                [ids,
                 self._lo + ((fill_base - self._lo + np.arange(pad))
                             % self.num_examples)])
        self._cursor = end
        data, label = self._rows(ids)
        return DataBatch(data=[data], label=[label], pad=pad,
                         index=ids.copy())

    def getpad(self):
        return max(0, self._cursor - self._hi)
