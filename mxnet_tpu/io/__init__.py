"""Data iterators (parity: ``python/mxnet/io/`` + ``src/io/``)."""
from .io import (  # noqa: F401
    DataDesc, DataBatch, DataIter, ResizeIter, PrefetchingIter,
    NDArrayIter, CSVIter, MNISTIter, ImageRecordIter,
)
from .libsvm import LibSVMIter, read_libsvm  # noqa: F401


def MXDataIter(*args, **kwargs):
    """The reference's wrapper over C-implemented iterators
    (``io.py MXDataIter``).  There is no C iterator registry here — the
    built-in iterators (ImageRecordIter, MNISTIter, CSVIter, LibSVMIter,
    NDArrayIter) are native Python/C++-data-plane classes — so this
    name exists only to give migrating code a actionable error."""
    from ..base import NotSupportedForTPU

    raise NotSupportedForTPU(
        "MXDataIter wraps the reference's C iterator handles, which do "
        "not exist in this runtime; construct the concrete iterator "
        "class instead (mx.io.ImageRecordIter / MNISTIter / CSVIter / "
        "LibSVMIter / NDArrayIter)")
