"""Data iterators (parity: ``python/mxnet/io/`` + ``src/io/``)."""
from .io import (  # noqa: F401
    DataDesc, DataBatch, DataIter, ResizeIter, PrefetchingIter,
    NDArrayIter, CSVIter, MNISTIter, ImageRecordIter,
)
from .libsvm import LibSVMIter, read_libsvm  # noqa: F401
