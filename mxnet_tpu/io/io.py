"""Data iterators.

Capability parity with the reference's Python iterator layer
(``python/mxnet/io/io.py``: ``DataIter``, ``DataBatch``, ``DataDesc``,
``NDArrayIter``, ``ResizeIter``, ``PrefetchingIter``) and the native
iterators it wraps (``src/io/``: ``iter_mnist.cc``, ``iter_csv.cc``,
``iter_image_recordio_2.cc``).

TPU-native design: batches are assembled on the host in NumPy (cheap,
parallel with device compute because the device step is async) and shipped
with one ``device_put`` per batch.  ``PrefetchingIter`` double-buffers with a
background thread exactly like the reference's ``PrefetcherIter``
(``src/io/iter_prefetcher.h:47``) so host decode overlaps TPU steps.
"""
from __future__ import annotations

import os
import struct
import gzip
import threading
from collections import namedtuple

import numpy as np

from ..base import MXNetError
from ..context import cpu
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray


class DataDesc(namedtuple('DataDesc', ['name', 'shape'])):
    """Data layout descriptor (parity: io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout='NCHW'):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (
            self.name, self.shape, self.dtype, self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find('N')

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One mini-batch (parity: io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), \
                "Data must be list of NDArrays"
        if label is not None:
            assert isinstance(label, (list, tuple)), \
                "Label must be list of NDArrays"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter:
    """Base data iterator (parity: io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


class ResizeIter(DataIter):
    """Resize an iterator to a fixed epoch length (parity: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, 'default_bucket_key'):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Double-buffered prefetch over one or more iterators.

    Parity: io.py PrefetchingIter / native ``PrefetcherIter``
    (``src/io/iter_prefetcher.h:47``) — a producer thread keeps the next
    batch ready so host decode overlaps the accelerator step.
    """

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i],
                             daemon=True)
            for i in range(self.n_iter)]
        for thread in self.prefetch_threads:
            thread.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([
            [DataDesc(r[x.name], x.shape, x.dtype)
             if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
             for x in i.provide_data]
            for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([
            [DataDesc(r[x.name], x.shape, x.dtype)
             if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
             for x in i.provide_label]
            for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iters"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Number of entry mismatches between iters"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _init_data(data, allow_empty, default_name):
    """Normalize data into a list of (name, numpy array) (parity: io_utils)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {default_name + '_%d' % i: d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError(
            "Input must be NDArray, numpy.ndarray, a list of them or dict "
            "with them as values")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()  # mxlint: allow-host-sync (serialization path)
        out.append((k, np.ascontiguousarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (parity: io.py NDArrayIter).

    Supports shuffle, pad/discard/roll_over last-batch handling.
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle='pad', data_name='data',
                 label_name='softmax_label'):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        self.num_source = len(self.data)
        self.cursor = -self.batch_size
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                     v.dtype)
            for k, v in self.data]

    @property
    def provide_label(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                     v.dtype)
            for k, v in self.label]

    def hard_reset(self):
        if self.shuffle:
            self._shuffle_data()
        self.cursor = -self.batch_size
        self._cache_data = None
        self._cache_label = None

    def reset(self):
        if self.shuffle:
            self._shuffle_data()
        # epoch ended mid-tail: negative cursor in (-batch_size, 0) makes
        # _batchify concat the cached tail with the head of this epoch
        if self.last_batch_handle == 'roll_over' and \
                self.num_data - self.batch_size < self.cursor < self.num_data:
            self.cursor = self.cursor - self.num_data - self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        data = self.getdata()
        label = self.getlabel()
        if data[0].shape[0] != self.batch_size:
            if self.last_batch_handle == 'discard':
                raise StopIteration
            # roll_over: cache the short tail for the next epoch and end
            # this one (the caller never sees an inconsistent-size batch)
            self._cache_data = data
            self._cache_label = label
            raise StopIteration
        return DataBatch(data=data, label=label,
                         pad=self.getpad(), index=None)

    def _getdata(self, data_source, start=None, end=None):
        assert start is not None or end is not None
        if start is None:
            start = 0
        if end is None:
            end = data_source[0][1].shape[0] if data_source else 0
        s = slice(start, end)
        return [nd.array(x[1][s]) for x in data_source]

    def _concat(self, first_data, second_data):
        if not first_data:
            return []
        return [
            nd.array(np.concatenate(
                (first_data[i].asnumpy(), second_data[i].asnumpy())))
            for i in range(len(first_data))]

    def _batchify(self, data_source):
        assert self.cursor < self.num_data
        if self.last_batch_handle == 'roll_over' and \
                -self.batch_size < self.cursor < 0:
            assert self._cache_data is not None or \
                self._cache_label is not None
            # getdata consumes _cache_data first, then getlabel finds it
            # cleared and consumes _cache_label — each cache is used once
            if self._cache_data is not None:
                cache, self._cache_data = self._cache_data, None
            else:
                cache, self._cache_label = self._cache_label, None
            second = self._getdata(
                data_source, end=self.cursor + self.batch_size)
            return self._concat(cache, second)
        if self.cursor + self.batch_size <= self.num_data:
            return self._getdata(
                data_source, self.cursor, self.cursor + self.batch_size)
        # tail: pad from head
        pad = self.batch_size - self.num_data + self.cursor
        first = self._getdata(data_source, self.cursor)
        if self.last_batch_handle == 'pad':
            second = self._getdata(data_source, end=pad)
            return self._concat(first, second)
        return first

    def getdata(self):
        return self._batchify(self.data)

    def getlabel(self):
        return self._batchify(self.label)

    def getpad(self):
        if self.last_batch_handle == 'pad' and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        if self.last_batch_handle == 'roll_over' and \
                -self.batch_size < self.cursor < 0:
            return -self.cursor
        return 0

    def _shuffle_data(self):
        np.random.shuffle(self.idx)
        self.data = [(k, v[self.idx]) for k, v in self.data]
        self.label = [(k, v[self.idx]) for k, v in self.label]


class CSVIter(NDArrayIter):
    """CSV file iterator (parity: ``src/io/iter_csv.cc``).

    Host-side: loads the csv(s) with numpy then batches like NDArrayIter.
    """

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 shuffle=False, **kwargs):
        data = np.loadtxt(data_csv, delimiter=',', dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=',', dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        super().__init__(
            data, label, batch_size=batch_size, shuffle=shuffle,
            last_batch_handle='pad' if round_batch else 'discard',
            label_name='label')


def _read_idx_images(path):
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rb') as f:
        magic, num, rows, cols = struct.unpack('>IIII', f.read(16))
        if magic != 2051:
            raise MXNetError("Bad magic %d in %s" % (magic, path))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(
            num, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rb') as f:
        magic, num = struct.unpack('>II', f.read(8))
        if magic != 2049:
            raise MXNetError("Bad magic %d in %s" % (magic, path))
        return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (parity: ``src/io/iter_mnist.cc``).

    Reads the standard idx(.gz) files from disk; no download (no egress).
    """

    def __init__(self, image='train-images-idx3-ubyte',
                 label='train-labels-idx1-ubyte', batch_size=128,
                 shuffle=True, flat=False, silent=False, seed=0,
                 **kwargs):
        if not os.path.exists(image):
            raise MXNetError("MNIST image file %s not found" % image)
        images = _read_idx_images(image).astype(np.float32) / 255.0
        labels = _read_idx_labels(label)
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1,
                                    images.shape[1], images.shape[2])
        super().__init__(images, labels, batch_size=batch_size,
                         shuffle=shuffle, label_name='softmax_label')


class ImageRecordIter(DataIter):
    """RecordIO image iterator (parity: ``src/io/iter_image_recordio_2.cc``).

    Reads RecordIO packs produced by ``tools/im2rec`` via the
    :mod:`mxnet_tpu.recordio` reader, decodes + augments on host threads,
    and yields NCHW float batches.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_width=1, shuffle=False, mean_r=0., mean_g=0.,
                 mean_b=0., scale=1.0, rand_crop=False, rand_mirror=False,
                 preprocess_threads=4, **kwargs):
        super().__init__(batch_size)
        from .. import recordio as rio
        from .. import image as img_mod

        self._unpack = rio.unpack_img
        self._record = rio.RecordIOIterable(path_imgrec)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.mean = np.array([mean_r, mean_g, mean_b],
                             dtype=np.float32).reshape(3, 1, 1)
        self.scale = scale
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self._native = None  # tri-state: None = try, False = opted out
        self._threads = max(1, int(preprocess_threads))
        self._img = img_mod
        self._records = list(self._record)
        self._order = np.arange(len(self._records))
        self.cursor = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc('data',
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc('softmax_label', shape)]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self._order)
        self.cursor = 0

    def iter_next(self):
        return self.cursor + self.batch_size <= len(self._records)

    def next(self):
        if not self.iter_next():
            raise StopIteration
        c, h, w = self.data_shape
        labels = np.empty((self.batch_size, self.label_width),
                          dtype=np.float32)
        data = self._next_native(c, h, w, labels)
        if data is None:
            data = np.empty((self.batch_size, c, h, w), dtype=np.float32)
            for i in range(self.batch_size):
                rec = self._records[self._order[self.cursor + i]]
                header, img = self._unpack(rec)
                arr = self._prep(img, h, w)
                data[i] = arr
                lbl = np.atleast_1d(np.asarray(header.label,
                                               dtype=np.float32))
                labels[i] = lbl[:self.label_width]
        self.cursor += self.batch_size
        label_out = labels[:, 0] if self.label_width == 1 else labels
        return DataBatch(data=[nd.array(data)],
                         label=[nd.array(label_out)], pad=0)

    def _next_native(self, c, h, w, labels):
        """Native fast path: the whole batch is decoded, cropped, resized,
        flipped and normalized by the C++ thread pool
        (src/image_decode_native.cc) in ONE call outside the GIL — the
        rebuild's ImageRecordIOParser2.  Crop/flip decisions come from the
        same np.random call sequence as _prep, so the two paths produce
        identical batches for a given seed; payload probing happens
        BEFORE any RNG draw so bailing to the python path never shifts
        the stream.  Returns the (N, C, H, W) float32 batch or None
        (non-JPEG payloads / non-RGB target / no native lib)."""
        from .. import native
        from .. import recordio as rio

        if self._native is False or c != 3                 or not native.jpeg_available():
            self._native = False
            return None
        # pass 1 (no RNG): unpack, verify JPEG, probe dims
        bufs, dims_list = [], []
        for i in range(self.batch_size):
            rec = self._records[self._order[self.cursor + i]]
            header, payload = rio.unpack(rec)
            if payload[:2] != b"\xff\xd8":  # not JPEG: python path
                self._native = False
                return None
            dims = native.jpeg_probe(payload)
            if dims is None:
                self._native = False
                return None
            bufs.append(payload)
            dims_list.append(dims)
            lbl = np.atleast_1d(np.asarray(header.label, dtype=np.float32))
            labels[i] = lbl[:self.label_width]
        # pass 2: draw crop/flip decisions in _prep's exact RNG order
        crops = np.empty((self.batch_size, 4), np.int64)
        flips = np.zeros(self.batch_size, np.uint8)
        for i, (ih, iw) in enumerate(dims_list):
            crop, flip = self._draw_aug(ih, iw, h, w)
            crops[i] = crop
            flips[i] = 1 if flip else 0
        self._native = True
        out, ok = native.decode_aug_batch(
            bufs, h, w, crops=crops, flips=flips, interp=0,
            mean=tuple(self.mean.reshape(-1)), scale=(self.scale,) * 3,
            nthreads=self._threads)
        if not ok.all():
            # strict libjpeg rejects streams PIL tolerates (truncated
            # scans): re-decode just the failed records on the python
            # path, REUSING the drawn crop/flip so the RNG stream and
            # augmentations stay identical to a pure-python run
            for i in np.nonzero(ok == 0)[0]:
                rec = self._records[self._order[self.cursor + i]]
                _, img = self._unpack(rec)
                out[i] = self._apply_aug(img, crops[i], bool(flips[i]),
                                         h, w)
        return out

    def _apply_aug(self, img, crop, flip, h, w):
        """Apply an already-drawn (crop, flip) decision the way _prep
        would — used by the native path's per-record fallback."""
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None].repeat(3, axis=2)
        ih, iw = arr.shape[:2]
        x0, y0, cw, ch = (int(v) for v in crop)
        if cw > 0 and ch > 0:
            arr = arr[y0:y0 + ch, x0:x0 + cw]
        else:  # full frame + nearest resize (matches _prep)
            yy = np.clip(
                (np.arange(h) * ih / float(h)).astype(int), 0, ih - 1)
            xx = np.clip(
                (np.arange(w) * iw / float(w)).astype(int), 0, iw - 1)
            arr = arr[yy][:, xx]
        if flip:
            arr = arr[:, ::-1]
        arr = arr.transpose(2, 0, 1)
        return (arr - self.mean) * self.scale

    def _prep(self, img, h, w):
        """Draw this record's (crop, flip) decision, then apply it via
        _apply_aug — the SAME function the native path's fallback uses,
        so the transform logic exists exactly once and the two paths
        cannot drift."""
        arr = np.asarray(img)
        ih, iw = arr.shape[:2]
        crop, flip = self._draw_aug(ih, iw, h, w)
        return self._apply_aug(img, crop, flip, h, w)

    def _draw_aug(self, ih, iw, h, w):
        """(crop_xywh, flip) for one record, consuming np.random in the
        canonical order (randint y, randint x, rand for mirror)."""
        if self.rand_crop and ih >= h and iw >= w:
            y0 = np.random.randint(0, ih - h + 1)
            x0 = np.random.randint(0, iw - w + 1)
            crop = (x0, y0, w, h)
        elif ih >= h and iw >= w:
            crop = ((iw - w) // 2, (ih - h) // 2, w, h)
        else:
            crop = (-1, -1, -1, -1)  # full frame + nearest resize
        flip = bool(self.rand_mirror and np.random.rand() < 0.5)
        return crop, flip
