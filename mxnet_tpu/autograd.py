"""Imperative autograd: tape + reverse pass.

Reference: ``python/mxnet/autograd.py`` + ``src/imperative/imperative.cc``
(``RecordOp:193`` builds grad-graph nodes; ``Backward:280`` runs the nnvm
``Gradient`` pass then executes the backward graph).

TPU-native design: instead of stashing ``AGInfo`` on nnvm nodes and re-deriving
a backward graph per op via ``FGradient``, every recorded op captures its XLA
VJP closure at invoke time (``jax.vjp`` over the op's jitted forward).  The
backward pass is then a pure tape walk — reverse topological order, calling
each node's VJP and accumulating cotangents.  Residuals live in device memory
as XLA buffers; recomputation/checkpointing is handled at the graph (hybridize)
level instead.
"""
from __future__ import annotations

import threading

import jax.numpy as jnp
import numpy as _np

from .base import MXNetError

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    # NOT a bulk-segment boundary: ops recorded under the tape defer into
    # segments like any other op (their TapeNode primals hold _BulkRefs
    # that resolve — flushing on demand — at backward time), so a whole
    # recorded forward fuses without the tape ever forcing a flush.
    st = _st()
    prev, st.recording = st.recording, bool(is_record)
    return prev


def set_training(train_mode):
    st = _st()
    prev, st.training = st.training, bool(train_mode)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._rec = is_record
        self._train = train_mode
        self._prev = None

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *args):
        st = _st()
        st.recording, st.training = self._prev


def record(train_mode=True):
    """Scope in which ops on marked arrays are taped (parity: autograd.record:122)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


class TapeNode:
    """One recorded op: VJP closure + graph edges.

    ``inputs`` are the NDArray objects fed to the op (leaf or intermediate),
    ``out_avals`` the (shape, dtype) of each op output so missing head
    gradients can be zero-filled, ``skip_grad_inputs`` marks leading non-
    differentiable args (e.g. RNG keys) whose cotangents are discarded.
    """

    __slots__ = (
        "vjp_fn",
        "inputs",
        "out_avals",
        "skip_grad_inputs",
        "cotangents",
        "op_name",
        "prim",
        "__weakref__",
    )

    def __init__(self, vjp_fn, inputs, out_avals, skip_grad_inputs=0, op_name="",
                 prim=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs
        self.out_avals = out_avals
        self.skip_grad_inputs = skip_grad_inputs
        self.cotangents = None
        self.op_name = op_name
        # (fn, datas, n_rng): the primal callable + raw input arrays, kept so
        # create_graph=True can RE-linearize (jax.vjp closures bake the primal
        # point in, so higher order needs the function itself; reference:
        # second-order FGradient entries like _backward_backward_FullyConnected,
        # src/operator/nn/fully_connected.cc:363)
        self.prim = prim

    def seed(self, idx, ct):
        if self.cotangents is None:
            self.cotangents = [None] * len(self.out_avals)
        dtype = self.out_avals[idx][1]
        if getattr(ct, "dtype", None) != dtype:
            # consumers may run in a different dtype than this op produced
            # (AMP dispatch-time casts); vjp demands exact cotangent dtypes
            ct = ct.astype(dtype)
        cur = self.cotangents[idx]
        self.cotangents[idx] = ct if cur is None else cur + ct

    def materialize_cotangents(self):
        if self.cotangents is None:
            self.cotangents = [None] * len(self.out_avals)
        outs = []
        for ct, (shape, dtype) in zip(self.cotangents, self.out_avals):
            if ct is None:
                ct = jnp.zeros(shape, dtype)
            outs.append(ct)
        return tuple(outs)


def _topo_order(root_nodes):
    """Reverse-topological (output→input) order over reachable tape nodes."""
    order = []
    seen = set()
    stack = [(n, False) for n in root_nodes]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for inp in node.inputs:
            child = inp._tape_node
            if child is not None and id(child) not in seen:
                stack.append((child, False))
    # order is inputs-before-outputs; backward wants outputs first
    order.reverse()
    return order


_backward_gen = [0]


def current_backward_gen():
    return _backward_gen[0]


def _resolve_prim_datas(datas):
    """Materialize any ``_BulkRef`` primals recorded through a segment.

    A TapeNode recorded while its op was deferred holds segment promises
    instead of concrete buffers; the first backward that needs one
    flushes its segment (one fused push) and reads the landed value —
    the tape itself never forces a flush at record time.
    """
    from .engine import _BulkRef

    if not any(type(d) is _BulkRef for d in datas):
        return datas
    out = []
    for d in datas:
        if type(d) is _BulkRef:
            if d.value is None and not d.failed:
                d.segment.flush("backward")
            if d.value is None:
                raise MXNetError(
                    "cannot run backward: a deferred forward value was "
                    "lost (its bulk segment failed)")
            out.append(d.value)
        else:
            out.append(d)
    return tuple(out)


def _node_backward(node, cts):
    """Run one node's backward.

    Nodes recorded by the lazy tape carry only their primal
    (``node.prim``); the vjp runs as ONE cached jitted executable per
    stable op callable (``fn._mx_bwd``), so neither recording nor
    backward re-traces ``jax.vjp`` per invocation — the tape-walk
    analogue of the reference executing a prebuilt backward graph.
    Ad-hoc closures (invoke_fn, control flow) linearize eagerly.
    """
    import jax

    if node.vjp_fn is not None:
        return node.vjp_fn(cts)
    fn, datas, _n_rng = node.prim
    datas = _resolve_prim_datas(datas)
    bwd = getattr(fn, "_mx_bwd", None)
    if bwd is None:
        def bwd_fn(primals, cotangents):
            _, vjp = jax.vjp(fn, *primals)
            return vjp(cotangents)

        if getattr(fn, "_mx_stable", False):
            bwd = jax.jit(bwd_fn)
            try:
                fn._mx_bwd = bwd
            except Exception:  # wrapper types that reject attributes
                pass
        else:
            bwd = bwd_fn
    return bwd(tuple(datas), tuple(cts))


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run the reverse pass from ``heads`` (parity: MXAutogradBackwardEx).

    Gradients accumulate into ``.grad`` of every reachable leaf that called
    ``attach_grad``.  ``train_mode`` is accepted for parity; the mode was
    already baked into the taped VJPs at record time (XLA closures are
    specialized, so there is no late mode switch — documented deviation).
    """
    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    if len(head_grads) != len(heads):
        raise MXNetError("len(head_grads) != len(heads)")
    _backward_gen[0] += 1

    roots = []
    for h, hg in zip(heads, head_grads):
        node = h._tape_node
        if node is None:
            if h._marked:
                # backward on a bare leaf: grad = head_grad (ones by default)
                g = hg.data() if hasattr(hg, "data") else (
                    jnp.ones(h.shape, h.dtype) if hg is None else jnp.asarray(hg)
                )
                h._accumulate_grad(g)
                continue
            raise MXNetError(
                "cannot differentiate a head that is not in the recorded graph"
            )
        g = (
            jnp.ones(h.shape, h.dtype)
            if hg is None
            else (hg.data() if hasattr(hg, "data") else jnp.asarray(hg))
        )
        node.seed(h._tape_index, g)
        roots.append(node)

    for node in _topo_order(roots):
        if node.cotangents is None:
            continue  # not on a path from any head
        if node.vjp_fn is None and node.prim is None:
            raise MXNetError(
                "graph already freed by a previous backward; "
                "pass retain_graph=True to backward() to reuse it"
            )
        cts = node.materialize_cotangents()
        # consume the seeds NOW: a later backward over a retained graph
        # must start from fresh cotangents, not accumulate onto these
        node.cotangents = None
        in_cts = _node_backward(node, cts)
        if not retain_graph:
            node.vjp_fn = None
            node.prim = None
        skip = node.skip_grad_inputs
        for inp, ct in zip(node.inputs, in_cts[skip:] if skip else in_cts):
            if ct is None:
                continue
            child = inp._tape_node
            if child is not None:
                if hasattr(ct, "tostype"):  # sparse ct into an interior node
                    ct = ct.tostype("default").data()
                child.seed(inp._tape_index, ct)
            elif inp._marked:
                inp._accumulate_grad(ct)

    # seeds were consumed node-by-node in the loop; nothing left to clear


def _apply_node_vjp_taped(node, cts):
    """Apply a node's backward as a RECORDED op (create_graph support).

    ``cts`` are NDArray cotangents for each node output.  Re-linearizes the
    stored primal (``node.prim``) so the produced input-cotangents carry
    their own tape nodes — grads of grads (and third order, recursively)
    just work.  Returns NDArray-or-None per ``node.inputs`` entry.
    """
    import jax

    from .ndarray.ndarray import NDArray

    raw_cts = tuple(c.data() for c in cts)
    if node.prim is None:
        # opaque vjp (custom Function, hybridized cache): first-order only
        raw = node.vjp_fn(raw_cts)
        skip = node.skip_grad_inputs
        raw = raw[skip:] if skip else raw
        return [None if g is None else NDArray(g) for g in raw]

    fn, datas, n_rng = node.prim
    datas = _resolve_prim_datas(datas)
    n_prim = len(datas)

    def full(*args):
        prim, ct = args[:n_prim], args[n_prim:]
        _, vjp = jax.vjp(fn, *prim)
        return vjp(tuple(ct))

    args = tuple(datas) + raw_cts
    outs, vjp2 = jax.vjp(full, *args)
    new_node = TapeNode(
        vjp2,
        list(node.inputs) + list(cts),
        [(o.shape, o.dtype) for o in outs],
        skip_grad_inputs=n_rng,
        op_name="_backward_" + node.op_name,
        prim=(full, args, n_rng),
    )
    results = []
    for i in range(n_rng, n_prim):
        arr = NDArray(outs[i])
        arr._tape_node = new_node
        arr._tape_index = i
        results.append(arr)
    return results


def _taped_backward(heads, head_grads, train_mode=True):
    """NDArray-valued reverse pass that records itself (create_graph=True).

    Returns ``{id(leaf NDArray): grad NDArray}`` for every reachable marked
    leaf; grad NDArrays carry tape nodes, so a second ``backward``/``grad``
    differentiates through them.
    """
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray

    seeds = {}
    node_by_id = {}

    def seed_nd(node, idx, ct):
        node_by_id[id(node)] = node
        lst = seeds.setdefault(id(node), [None] * len(node.out_avals))
        lst[idx] = ct if lst[idx] is None else lst[idx] + ct

    leaf_grads = {}

    def leaf_nd(leaf, ct):
        cur = leaf_grads.get(id(leaf))
        leaf_grads[id(leaf)] = ct if cur is None else cur + ct

    roots = []
    with record(train_mode):
        for h, hg in zip(heads, head_grads):
            g = hg if isinstance(hg, NDArray) else NDArray(
                jnp.ones(h.shape, h.dtype) if hg is None
                else jnp.asarray(hg))
            node = h._tape_node
            if node is None:
                if h._marked:
                    leaf_nd(h, g)
                    continue
                raise MXNetError(
                    "cannot differentiate a head that is not in the "
                    "recorded graph")
            seed_nd(node, h._tape_index, g)
            roots.append(node)

        for node in _topo_order(roots):
            lst = seeds.get(id(node))
            if lst is None:
                continue
            cts = [c if c is not None else NDArray(jnp.zeros(s, d))
                   for c, (s, d) in zip(lst, node.out_avals)]
            in_cts = _apply_node_vjp_taped(node, cts)
            for inp, ct in zip(node.inputs, in_cts):
                if ct is None:
                    continue
                child = inp._tape_node
                if child is not None:
                    seed_nd(child, inp._tape_index, ct)
                elif inp._marked:
                    leaf_nd(inp, ct)
    return leaf_grads


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Return grads of ``heads`` w.r.t. ``variables`` without touching ``.grad``.

    Parity: ``autograd.grad`` (python/mxnet/autograd.py:273).  With
    ``create_graph=True`` the backward pass itself is recorded (each node's
    primal is re-linearized via ``jax.vjp``), so the returned grads can be
    differentiated again — arbitrary order (ref test_higher_order_grad.py).
    """
    from .ndarray.ndarray import NDArray

    if create_graph:
        single = isinstance(variables, NDArray)
        var_list = [variables] if single else list(variables)
        if isinstance(heads, NDArray):
            heads = [heads]
            if head_grads is not None and not isinstance(
                    head_grads, (list, tuple)):
                head_grads = [head_grads]
        if head_grads is None:
            head_grads = [None] * len(heads)
        saved = [v._marked for v in var_list]
        for v in var_list:
            v._marked = True
        try:
            leaf_map = _taped_backward(heads, head_grads, train_mode)
        finally:
            for v, m in zip(var_list, saved):
                v._marked = m
        outs = []
        for v in var_list:
            g = leaf_map.get(id(v))
            if g is None:
                import jax.numpy as jnp

                g = NDArray(jnp.zeros(v.shape, v.dtype), ctx=v.context)
            outs.append(g)
        return outs[0] if single else outs
    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    saved = [(v._grad, v._grad_req) for v in variables]
    for v in variables:
        v._grad = None
        v._grad_req = "add"
        v._marked = True
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
        outs = []
        for v in variables:
            if v._grad is None:
                outs.append(NDArray(jnp.zeros(v.shape, v.dtype), ctx=v.context))
            else:
                outs.append(NDArray(v._grad, ctx=v.context))
    finally:
        for v, (g, req) in zip(variables, saved):
            v._grad, v._grad_req = g, req
    return outs[0] if single else outs


class Function:
    """Custom-gradient block (parity: autograd.Function, autograd.py:370)."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):  # pragma: no cover - abstract
        raise NotImplementedError

    def backward(self, *output_grads):  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, array

        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, NDArray)
        outs = [outputs] if single else list(outputs)
        if is_recording() and any(
            isinstance(i, NDArray) and i._in_graph for i in inputs
        ):
            nd_inputs = [i for i in inputs if isinstance(i, NDArray)]

            def vjp_fn(cts):
                with pause():
                    igrads = self.backward(*[NDArray(c) for c in cts])
                if isinstance(igrads, NDArray):
                    igrads = [igrads]
                return tuple(
                    g.data() if isinstance(g, NDArray) else g for g in igrads
                )

            node = TapeNode(
                vjp_fn,
                nd_inputs,
                [(o.shape, o.dtype) for o in outs],
                op_name=type(self).__name__,
            )
            for i, o in enumerate(outs):
                o._tape_node = node
                o._tape_index = i
        return outputs


def mark_variables(variables, gradients, grad_reqs="write"):
    """Parity: autograd.mark_variables / Imperative::MarkVariables."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._marked = True
        v._grad = g.data() if hasattr(g, "data") else g
        v._grad_req = req
