"""Parity for ``mx.libinfo`` (reference ``python/mxnet/libinfo.py``).

The reference locates ``libmxnet.so`` and reports its version; here the
"library" is the package itself (XLA is the kernel library) plus the
optional native data-plane helpers, so ``find_lib_path`` returns the
built native ``.so`` paths when present.
"""
import os

from . import __version__  # noqa: F401


def find_lib_path():
    """Paths of the native helper libraries built for this install
    (reference returns [libmxnet.so]).  May be empty: the compute path
    needs no native library — XLA provides the kernels."""
    import glob

    from . import native

    try:
        native.get_lib()  # ensure the cached build exists
    except Exception:
        pass
    return sorted(glob.glob(os.path.join(native._cache_dir(),
                                         "mxnet_native-*.so")))


def find_include_path():
    """Headers for binary extensions (reference: include/mxnet)."""
    src = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src")
    return src if os.path.isdir(src) else ""
