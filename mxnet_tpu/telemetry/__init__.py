"""``mx.telemetry`` — always-on metrics, flight recorder, memory
accounting + cross-process trace merging.

See docs/observability.md.  Quick tour::

    import mxnet_tpu as mx
    mx.telemetry.counter("my_counter_total", my_label="x").inc()
    mx.telemetry.snapshot()          # JSON-able dict of every family
    print(mx.telemetry.prometheus_text())
    mx.telemetry.dump("metrics.prom")

    # one timeline from N per-process profiler dumps
    mx.telemetry.merge_traces(["worker0.json", "server.json"],
                              out="merged.json")

    # black-box forensics: last 4096 framework events, crash-dumped
    mx.telemetry.flight.events(kind="kv", last=10)
    mx.telemetry.flight.dump("flight.json")

    # who owns the device memory?
    mx.telemetry.memdump.device_bytes()   # {"param": ..., "kv_page": ...}

    # fleet-wide: merge N replica snapshots, evaluate SLO burn rates
    mx.telemetry.aggregate.merge_snapshots({"r0": snap0, "r1": snap1})
    mx.telemetry.slo.SLOEngine().observe(merged)
"""
from .metrics import (  # noqa: F401
    counter, gauge, histogram,
    enabled, enable, disable,
    snapshot, prometheus_text, render_text, dump, reset,
    register_collector, record_compile,
)
from .trace import merge_traces  # noqa: F401
from . import aggregate  # noqa: F401
from . import flight  # noqa: F401
from . import memdump  # noqa: F401
from . import slo  # noqa: F401
