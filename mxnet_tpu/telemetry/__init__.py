"""``mx.telemetry`` — always-on metrics + cross-process trace merging.

See docs/observability.md.  Quick tour::

    import mxnet_tpu as mx
    mx.telemetry.counter("my_counter_total", my_label="x").inc()
    mx.telemetry.snapshot()          # JSON-able dict of every family
    print(mx.telemetry.prometheus_text())
    mx.telemetry.dump("metrics.prom")

    # one timeline from N per-process profiler dumps
    mx.telemetry.merge_traces(["worker0.json", "server.json"],
                              out="merged.json")
"""
from .metrics import (  # noqa: F401
    counter, gauge, histogram,
    enabled, enable, disable,
    snapshot, prometheus_text, dump, reset,
    register_collector, record_compile,
)
from .trace import merge_traces  # noqa: F401
