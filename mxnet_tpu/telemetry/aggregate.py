"""Fleet-wide metric aggregation (docs/observability.md "Fleet
observability").

One serving fleet is N metric registries: each HTTP replica exposes its
own process-global registry (``GET /metrics.json``), in-process replicas
share ONE registry but carry per-server aggregates in their scheduler
stats.  This module merges those per-replica snapshots into one
fleet-level snapshot with Prometheus-sound semantics:

* **counters sum** across replicas per label-set (a fleet total is the
  only number an alert can threshold);
* **gauges keep per-replica series** — a ``replica`` label is added, so
  the fleet view shows three queue depths, not their meaningless sum;
* **histograms merge bucket-wise**: cumulative bucket counts, ``sum``
  and ``count`` add (a sum of cumulative counts is the cumulative count
  of the union), so ``histogram_quantile`` over the merged series is
  the fleet-wide quantile.

Every function here works on :func:`telemetry.snapshot`-shaped dicts —
``{family: {"type", "help", "series": [...]}}`` — never on live metric
objects, so aggregation is pure and scrape-time cheap.

:func:`snapshot_from_stats` synthesizes a snapshot-shaped doc from one
replica's ``/healthz`` stats: the in-process fleet (bench, chaos matrix,
3-replicas-one-process CI jobs) shares a single registry, so scraping it
per replica would multiply every count by N — the per-server scheduler
aggregates are the only honestly per-replica numbers in that topology.
"""
from __future__ import annotations

__all__ = ["merge_snapshots", "snapshot_from_stats", "overlay"]


def _series_key(labels):
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def merge_snapshots(snaps):
    """Merge ``{replica_name: snapshot}`` into one fleet snapshot.

    Counters sum per label-set; gauges gain a ``replica`` label and keep
    one series per replica; histogram series merge bucket-wise per
    label-set.  Replica order is normalized (sorted) so the merge of
    the same inputs is byte-identical regardless of scrape order.
    """
    merged = {}
    acc = {}     # (family, series_key) -> accumulating entry
    for replica in sorted(snaps):
        snap = snaps[replica] or {}
        for name, fam in snap.items():
            out = merged.setdefault(
                name, {"type": fam.get("type", "counter"),
                       "help": fam.get("help", ""), "series": []})
            for s in fam.get("series", []):
                labels = dict(s.get("labels", {}))
                if out["type"] == "gauge":
                    labels["replica"] = replica
                    out["series"].append(
                        {"labels": labels, "value": s.get("value", 0)})
                    continue
                key = (name, _series_key(labels))
                entry = acc.get(key)
                if entry is None:
                    entry = {"labels": labels}
                    if out["type"] == "histogram":
                        entry.update(buckets={}, sum=0.0, count=0)
                    else:
                        entry["value"] = 0
                    acc[key] = entry
                    out["series"].append(entry)
                if out["type"] == "histogram":
                    for bound, c in s.get("buckets", {}).items():
                        entry["buckets"][bound] = \
                            entry["buckets"].get(bound, 0) + c
                    entry["sum"] += s.get("sum", 0.0)
                    entry["count"] += s.get("count", 0)
                else:
                    entry["value"] += s.get("value", 0)
    # histogram buckets render in ascending-bound order with +Inf last,
    # whatever order the inputs carried them in
    for name, fam in merged.items():
        if fam["type"] != "histogram":
            continue
        for entry in fam["series"]:
            items = sorted(entry["buckets"].items(),
                           key=lambda bc: (bc[0] == "+Inf",
                                           float(bc[0])
                                           if bc[0] != "+Inf" else 0.0))
            entry["buckets"] = dict(items)
    return merged


def overlay(merged, local):
    """Fill ``merged`` with families from ``local`` (the router's own
    registry snapshot) that the per-replica merge didn't produce.

    The replica-merged families win: in an in-process fleet the local
    registry holds the same underlying counts the per-replica synthesis
    already attributed, so adding them again would double-count.  The
    local snapshot contributes only what no replica scrape carries —
    the ``mxnet_fleet_*`` routing families, ``mxnet_slo_*`` gauges, and
    (in-process) the shared latency histograms.  Returns ``merged``.
    """
    for name, fam in (local or {}).items():
        if name not in merged:
            merged[name] = fam
    return merged


# per-server scheduler aggregates -> synthesized snapshot families.
# (family, kind, help, stats key); counters sum at merge, gauges get the
# replica label.  Gauges reuse the canonical registry names on purpose
# (a replica-labeled queue depth strictly improves on the registry's
# last-writer-wins single gauge, and overlay() lets the merged family
# win); counters get a distinct ``_replica_`` namespace so they can
# never mask a richer registry family (``mxnet_serve_requests_total``
# carries per-status labels the scheduler stats don't).  Latency
# percentiles stay out — percentiles are not mergeable (the shared
# in-process histograms cover them via overlay()).
_STATS_FAMILIES = (
    ("mxnet_serve_queue_depth", "gauge",
     "requests waiting for admission", "queue_len"),
    ("mxnet_serve_batch_occupancy", "gauge",
     "active decode slots (of max_batch)", "active_slots"),
    ("mxnet_serve_arena_utilization", "gauge",
     "fraction of arena pages in use", "arena_utilization"),
    ("mxnet_serve_sessions_active", "gauge",
     "pinned chat sessions holding arena pages between turns",
     "sessions"),
    ("mxnet_serve_replica_admitted_total", "counter",
     "requests admitted, per replica scrape", "admitted"),
    ("mxnet_serve_replica_completed_total", "counter",
     "requests completed, per replica scrape", "completed"),
    ("mxnet_serve_replica_tokens_total", "counter",
     "tokens generated, per replica scrape", "tokens_generated"),
    ("mxnet_serve_replica_decode_steps_total", "counter",
     "decode steps executed, per replica scrape", "decode_steps"),
)


def snapshot_from_stats(stats):
    """Synthesize a snapshot-shaped dict from one replica's ``healthz``/
    ``stats`` doc — the per-replica scrape for in-process fleets, where
    the process-global registry can't attribute anything to one
    replica.  Unknown/missing keys are skipped, never defaulted: a
    missing aggregate must not masquerade as a zero."""
    out = {}
    for name, kind, help_, key in _STATS_FAMILIES:
        if key not in (stats or {}):
            continue
        out[name] = {"type": kind, "help": help_,
                     "series": [{"labels": {}, "value": stats[key]}]}
    return out
