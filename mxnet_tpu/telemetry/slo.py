"""SLO / error-budget engine with multi-window burn-rate alerts
(docs/observability.md "Fleet observability").

An SLO is a target fraction of *good* events — requests that succeeded,
TTFTs under a threshold — over a rolling window.  The error budget is
the allowed bad fraction (``1 - objective``); the **burn rate** is how
fast the fleet is spending it: ``bad_fraction / (1 - objective)``.  A
burn rate of 1.0 exhausts the budget exactly at the window's horizon;
14.4 exhausts a 30-day budget in 2 days — the classic paging threshold.

Objectives are declarative dicts evaluated over the *aggregated* metric
stream (``telemetry.aggregate``), never over a single replica:

* ``availability`` — good/total from a counter family with a status
  label (``{"name": "availability", "objective": 0.99,
  "family": "mxnet_fleet_requests_total",
  "good_label": ["status", "ok"]}``);
* ``latency`` — good = observations at or under ``threshold_s`` read
  from a histogram family's cumulative buckets
  (``{"name": "ttft_p99", "objective": 0.99,
  "family": "mxnet_serve_ttft_seconds", "threshold_s": 0.5}``).

Evaluation is **two-window**: a fast window catches an active outage, a
slow window keeps one bad scrape from paging.  The engine is
edge-triggered — one ``slo.burn`` flight event when an objective
*enters* the burning state, one ``slo.clear`` when it leaves — so a
seeded outage produces exactly one alert, run-twice identical.  State
surfaces as ``mxnet_slo_*`` gauges; an ``on_burn``/``on_clear`` pair
lets the FleetRouter shed optional work (hedging) while the fast window
burns (``MXNET_FLEET_SLO_SHED``).

The engine holds no threads and reads no wall clock of its own: the
caller feeds ``observe(snapshot, now)`` on its own cadence (the fleet
prober does), and an injectable clock keeps the chaos matrix
deterministic.
"""
from __future__ import annotations

import collections
import json
import time

from ..base import MXNetError
from . import flight as _flight
from . import metrics as _metrics

__all__ = ["SLOEngine", "default_objectives", "parse_objectives"]


def default_objectives():
    """The stock fleet objectives (``MXNET_FLEET_SLO=1``): availability
    plus the ROADMAP item 1 latency bars, TTFT p99 and TPOT p50."""
    return [
        {"name": "availability", "objective": 0.99,
         "family": "mxnet_fleet_requests_total",
         "good_label": ["status", "ok"]},
        {"name": "ttft_p99", "objective": 0.99,
         "family": "mxnet_serve_ttft_seconds", "threshold_s": 0.5},
        {"name": "tpot_p50", "objective": 0.50,
         "family": "mxnet_serve_tpot_seconds", "threshold_s": 0.05},
    ]


def parse_objectives(spec):
    """``MXNET_FLEET_SLO`` accepts ``1`` (stock objectives), an inline
    JSON list, or a path to a JSON file holding one."""
    spec = (spec or "").strip()
    if not spec:
        return []
    if spec == "1":
        return default_objectives()
    if spec.startswith("["):
        return json.loads(spec)
    with open(spec) as f:
        return json.load(f)


def _good_total(obj, snapshot):
    """Cumulative (good, total) for one objective from one aggregated
    snapshot; None when the family has no data yet."""
    fam = (snapshot or {}).get(obj["family"])
    if fam is None:
        return None
    series = fam.get("series", [])
    if "threshold_s" in obj:
        # latency: good = observations <= the smallest bucket bound
        # covering threshold_s (conservative: a coarse ladder rounds
        # the threshold UP, never silently relaxes it)
        good = total = 0
        thr = float(obj["threshold_s"])
        for s in series:
            buckets = s.get("buckets", {})
            finite = sorted((float(b), c) for b, c in buckets.items()
                            if b != "+Inf")
            covering = next((c for bound, c in finite if bound >= thr),
                            None)
            if covering is None:    # threshold above the ladder: all good
                covering = s.get("count", 0)
            good += covering
            total += s.get("count", 0)
        return (good, total)
    key, val = obj.get("good_label", ["status", "ok"])
    good = sum(s.get("value", 0) for s in series
               if str(s.get("labels", {}).get(key)) == str(val))
    total = sum(s.get("value", 0) for s in series)
    return (good, total)


class SLOEngine:
    """Evaluates declarative objectives over aggregated snapshots; see
    the module docstring.  Thread-compatible, not thread-safe: one
    caller (the fleet prober) owns ``observe``."""

    def __init__(self, objectives=None, fast_window_s=60.0,
                 slow_window_s=600.0, burn_threshold=2.0,
                 clock=time.monotonic, on_burn=None, on_clear=None):
        if objectives is None:
            objectives = default_objectives()
        elif isinstance(objectives, str):
            objectives = parse_objectives(objectives)
        self.objectives = []
        for obj in objectives:
            obj = dict(obj)
            if "name" not in obj or "family" not in obj:
                raise MXNetError(
                    "SLO objective needs 'name' and 'family': %r" % (obj,))
            target = float(obj.get("objective", 0.99))
            if not 0.0 < target < 1.0:
                raise MXNetError(
                    "SLO objective %r must be in (0, 1), got %r"
                    % (obj["name"], target))
            obj["objective"] = target
            self.objectives.append(obj)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._on_burn = on_burn
        self._on_clear = on_clear
        # (t, {name: (good, total)}) cumulative samples, slow-window deep
        self._samples = collections.deque()
        self._burning = {o["name"]: False for o in self.objectives}

    def burning(self, name=None):
        """Is ``name`` (or, with no argument, anything) burning?"""
        if name is not None:
            return self._burning.get(name, False)
        return any(self._burning.values())

    @staticmethod
    def _burn(new, old, objective):
        """Burn rate over the delta between two cumulative samples;
        0.0 when no events landed in the window (no news is good news —
        an idle fleet must not page)."""
        if new is None or old is None:
            return 0.0
        d_total = new[1] - old[1]
        if d_total <= 0:
            return 0.0
        d_bad = d_total - (new[0] - old[0])
        return (d_bad / d_total) / (1.0 - objective)

    def _window_base(self, now, window_s):
        """Newest sample at or older than the window start — the
        comparison base for the cumulative delta.  Falls back to the
        oldest retained sample while history is still shorter than the
        window (a young engine burns on what it has seen)."""
        base = None
        for t, vals in self._samples:
            if t <= now - window_s:
                base = vals
            else:
                break
        if base is None and self._samples:
            base = self._samples[0][1]
        return base

    def observe(self, snapshot, now=None):
        """Feed one aggregated snapshot; returns
        ``{name: {"burn_fast", "burn_slow", "burning",
        "budget_remaining"}}`` and fires the edge-triggered events."""
        now = self._clock() if now is None else now
        current = {o["name"]: _good_total(o, snapshot)
                   for o in self.objectives}
        fast_base = self._window_base(now, self.fast_window_s)
        slow_base = self._window_base(now, self.slow_window_s)
        self._samples.append((now, current))
        while self._samples and \
                self._samples[0][0] < now - self.slow_window_s:
            self._samples.popleft()
        out = {}
        for obj in self.objectives:
            name, target = obj["name"], obj["objective"]
            burn_fast = self._burn(
                current[name],
                fast_base.get(name) if fast_base else None, target)
            burn_slow = self._burn(
                current[name],
                slow_base.get(name) if slow_base else None, target)
            # budget remaining over the slow window: burn 1.0 for the
            # whole window spends it all
            remaining = max(0.0, 1.0 - burn_slow)
            # page on the fast window, but only while the slow window
            # confirms real spend — one bad scrape against an idle slow
            # window must not flap the alert
            burning = (burn_fast >= self.burn_threshold
                       and burn_slow >= 1.0)
            self._export(name, burn_fast, burn_slow, burning, remaining)
            if burning != self._burning[name]:
                self._burning[name] = burning
                self._edge(name, burning, burn_fast, burn_slow)
            out[name] = {"burn_fast": burn_fast, "burn_slow": burn_slow,
                         "burning": burning,
                         "budget_remaining": remaining}
        return out

    def _export(self, name, burn_fast, burn_slow, burning, remaining):
        if not _metrics.enabled():
            return
        _metrics.gauge(
            "mxnet_slo_burn_rate",
            help="error-budget burn rate per objective and window",
            slo=name, window="fast").set(round(burn_fast, 6))
        _metrics.gauge(
            "mxnet_slo_burn_rate", slo=name,
            window="slow").set(round(burn_slow, 6))
        _metrics.gauge(
            "mxnet_slo_error_budget_remaining",
            help="slow-window error budget left (1 = untouched)",
            slo=name).set(round(remaining, 6))
        _metrics.gauge(
            "mxnet_slo_burning",
            help="1 while the objective's burn alert is firing",
            slo=name).set(1 if burning else 0)

    def _edge(self, name, burning, burn_fast, burn_slow):
        if burning:
            _flight.record("slo.burn", slo=name,
                           burn_fast=round(burn_fast, 4),
                           burn_slow=round(burn_slow, 4))
            if _metrics.enabled():
                _metrics.counter(
                    "mxnet_slo_burn_events_total",
                    help="burn alerts fired (edge-triggered)",
                    slo=name).inc()
            if self._on_burn is not None:
                self._on_burn(name)
        else:
            _flight.record("slo.clear", slo=name,
                           burn_fast=round(burn_fast, 4),
                           burn_slow=round(burn_slow, 4))
            if self._on_clear is not None:
                self._on_clear(name)
