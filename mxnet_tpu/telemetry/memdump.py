"""Device-memory accounting: who owns every live device buffer?

Buffer donation (PR 6) and the paged KV arena (PR 8) made device-memory
ownership invisible — the exact class of bug (the CPU donation heap
corruption) we already hit blind.  This module answers two questions:

- **"what is on the device right now, and why?"** — live accounting by
  *origin* (``param`` / ``activation`` / ``kv_page`` / ``temp`` /
  ``grad``), exported as ``mxnet_device_bytes{origin}`` gauges plus a
  ``mxnet_device_peak_bytes`` watermark.
- **"what was on the device when we OOMed?"** — a RESOURCE_EXHAUSTED
  interceptor (wired into the engine's push/flush exception paths) that
  dumps the top-K largest buffers with their origin, label, and the
  flight-recorder seq of their allocation, before re-raising.

Design: **zero hot-path cost**.  The authoritative live set is
``jax.live_arrays()``, walked only at snapshot time (a telemetry
collector, same pattern as the engine stats).  Tags add *attribution*
and are applied only at low-frequency allocation sites — host→device
uploads in ``NDArray.__init__``, ``attach_grad``, KV-arena page
buffers, serving weight upload — never per-op: an untagged live buffer
is attributed to ``temp`` (op temporaries are exactly the buffers that
churn too fast to be worth tagging).  Tag liveness rides on
``weakref.finalize``; a periodic sweep against the live set prunes
anything a finalizer missed, so ``id()`` reuse cannot misattribute.
"""
from __future__ import annotations

import contextlib
import contextvars
import json
import os
import sys
import threading
import weakref

import jax

from ..base import atomic_path, env_flag
from . import flight
from .metrics import gauge, register_collector
# safe here (and only here in this package): telemetry/__init__ imports
# metrics and flight BEFORE memdump, and lockcheck needs exactly those
from ..testing import lockcheck as _lockcheck

__all__ = [
    "origin", "current_origin", "tag", "refresh", "device_bytes",
    "per_device_bytes", "peak_bytes", "topk", "reconcile", "is_oom",
    "oom_report", "maybe_oom_report", "enabled", "reset",
]

ORIGINS = ("param", "activation", "kv_page", "temp", "grad")

_ENABLED = env_flag("MXNET_MEMDUMP", True)

_origin_var = contextvars.ContextVar("mxnet_memdump_origin", default="temp")

_lock = _lockcheck.named_lock("telemetry.memdump")
_tags = {}          # id(jax.Array) -> dict(ref, origin, nbytes, seq, ...)
_seen_origins = set(ORIGINS)
_peak = 0
_freed_count = 0
_freed_bytes = 0


def enabled():
    return _ENABLED


def current_origin():
    return _origin_var.get()


@contextlib.contextmanager
def origin(name):
    """Scope: buffers tagged inside are attributed to ``name``.

    >>> with memdump.origin("param"):
    ...     w = mx.nd.array(weights)
    """
    tok = _origin_var.set(name)
    try:
        yield
    finally:
        _origin_var.reset(tok)


def _on_free(key, nbytes):
    global _freed_count, _freed_bytes
    with _lock:
        if _tags.pop(key, None) is not None:
            _freed_count += 1
            _freed_bytes += nbytes


def tag(buf, origin=None, label=None):
    """Attribute ``buf`` (a ``jax.Array``) to an origin.  Called at
    allocation sites, NOT per-op.  Returns the flight seq of the
    ``mem.tag`` event (or -1 when disabled / untaggable)."""
    if not _ENABLED or buf is None or not isinstance(buf, jax.Array):
        return -1
    o = origin or _origin_var.get()
    try:
        nbytes = int(buf.nbytes)
    except Exception:
        return -1
    seq = flight.record("mem.tag", origin=o, nbytes=nbytes,
                        label=label or "")
    key = id(buf)
    rec = {"ref": weakref.ref(buf), "origin": o, "nbytes": nbytes,
           "seq": seq, "label": label or "",
           "shape": tuple(getattr(buf, "shape", ())),
           "dtype": str(getattr(buf, "dtype", "?"))}
    with _lock:
        _tags[key] = rec
        _seen_origins.add(o)
    try:
        weakref.finalize(buf, _on_free, key, nbytes)
    except TypeError:
        pass  # unweakrefable backend array: the sweep prunes it instead
    return seq


def _sweep():
    """Walk the live set, attribute bytes by origin, prune dead tags.
    Returns ``(by_origin, total, live_tagged, live_untagged)``."""
    live = jax.live_arrays()
    by = dict.fromkeys(_seen_origins, 0)
    tagged = untagged = 0
    live_keys = set()
    with _lock:
        tags = dict(_tags)
    for a in live:
        try:
            nbytes = int(a.nbytes)
        except Exception:
            continue
        key = id(a)
        rec = tags.get(key)
        # identity check defeats id() reuse if a finalizer was missed
        if rec is not None and rec["ref"]() is a:
            by[rec["origin"]] = by.get(rec["origin"], 0) + nbytes
            tagged += 1
            live_keys.add(key)
        else:
            by["temp"] = by.get("temp", 0) + nbytes
            untagged += 1
    with _lock:
        for key in list(_tags):
            if key not in live_keys and _tags[key]["ref"]() is None:
                del _tags[key]
    return by, sum(by.values()), tagged, untagged


def refresh():
    """Recompute live device bytes, publish the gauges, advance the peak
    watermark.  Returns ``(by_origin, total_bytes)``.  Snapshot-time
    cost only — this is the registered telemetry collector."""
    global _peak
    by, total, _, _ = _sweep()
    with _lock:
        if total > _peak:
            _peak = total
    for o, v in sorted(by.items()):
        gauge("mxnet_device_bytes", help="live device bytes by origin",
              origin=o).set(v)
    gauge("mxnet_device_peak_bytes",
          help="peak observed live device bytes (sampled at snapshots, "
               "OOM reports and explicit refresh)").set(_peak)
    return by, total


def device_bytes():
    """Live device bytes by origin (runs a sweep)."""
    return refresh()[0]


def per_device_bytes(device=None, label_prefix=None):
    """Bytes resident on ONE device, by origin.

    ``device_bytes()`` counts each array's *logical* ``nbytes`` — a
    tp-sharded weight counts fully even though every device holds only
    a slice.  This sums the actual shard bytes resident on ``device``
    (default: the first local device), which is the quantity a
    per-device capacity — and the planner's ``spmd_cost`` prediction —
    is about.  ``label_prefix`` restricts the count to tags whose label
    starts with it (e.g. ``"train_step:"``), excluding untagged
    buffers; origins stay keyed as in :func:`device_bytes`.
    """
    if device is None:
        device = jax.local_devices()[0]
    with _lock:
        tags = dict(_tags)
    by = dict.fromkeys(_seen_origins, 0)
    for a in jax.live_arrays():
        try:
            nbytes = sum(int(s.data.nbytes) for s in a.addressable_shards
                         if s.device == device)
        except Exception:
            continue
        if not nbytes:
            continue
        rec = tags.get(id(a))
        if rec is not None and rec["ref"]() is a:
            if label_prefix is not None \
                    and not rec["label"].startswith(label_prefix):
                continue
            by[rec["origin"]] = by.get(rec["origin"], 0) + nbytes
        elif label_prefix is None:
            by["temp"] = by.get("temp", 0) + nbytes
    return by


def peak_bytes():
    """The peak watermark as of the last :func:`refresh`/sweep."""
    with _lock:
        return _peak


def topk(k=None):
    """The K largest live *tagged* buffers, as attribution dicts
    (origin, nbytes, shape, dtype, label, flight seq)."""
    if k is None:
        k = int(os.environ.get("MXNET_MEMDUMP_TOPK", "20") or 20)
    live = jax.live_arrays()
    with _lock:
        tags = dict(_tags)
    out = []
    for a in live:
        rec = tags.get(id(a))
        if rec is not None and rec["ref"]() is a:
            out.append({"origin": rec["origin"], "nbytes": rec["nbytes"],
                        "shape": list(rec["shape"]), "dtype": rec["dtype"],
                        "label": rec["label"], "flight_seq": rec["seq"]})
    out.sort(key=lambda r: -r["nbytes"])
    return out[:k]


def reconcile():
    """Cross-check frees/donations against the engine's own stats — a
    drifting delta between ``finalized_frees`` and what the engine
    thinks it donated is how the donation heap bug would have surfaced
    *before* corrupting anything."""
    from ..engine import Engine
    by, total, tagged, untagged = _sweep()
    stats = Engine.get().stats
    return {
        "live_bytes": total,
        "live_by_origin": by,
        "live_tagged": tagged,
        "live_untagged": untagged,
        "finalized_frees": _freed_count,
        "finalized_bytes": _freed_bytes,
        "engine_donated": getattr(stats, "bulk_donated", 0),
        "engine_ops_pushed": getattr(stats, "ops_pushed", 0),
    }


# ----------------------------------------------------------------------
# OOM interception
# ----------------------------------------------------------------------
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM: ", "Allocator ran out")


def is_oom(exc):
    s = "%s: %s" % (type(exc).__name__, exc)
    return any(m in s for m in _OOM_MARKERS)


def oom_report(exc, path=None):
    """Dump the attribution story of an OOM: totals by origin + top-K
    buffers with their allocation flight seqs.  Writes JSON to ``path``
    (default ``MXNET_MEMDUMP_PATH`` when set), always prints a compact
    table to stderr and records a ``mem.oom`` flight event.  Never
    raises — the caller re-raises the original error."""
    try:
        by, total = refresh()
        top = topk()
        doc = {"error": "%s: %s" % (type(exc).__name__, exc),
               "total_bytes": total, "by_origin": by,
               "peak_bytes": peak_bytes(), "topk": top}
        flight.record("mem.oom", total=total,
                      error=type(exc).__name__)
        flight.crash_dump("oom")
        lines = ["[mxnet_tpu] device OOM: %d live bytes" % total]
        for o, v in sorted(by.items(), key=lambda kv: -kv[1]):
            if v:
                lines.append("  %-12s %d" % (o, v))
        for r in top[:5]:
            lines.append("  top: %s %s %s %db (flight seq %s)"
                         % (r["origin"], r["dtype"], r["shape"],
                            r["nbytes"], r["flight_seq"]))
        print("\n".join(lines), file=sys.stderr)
        path = path or os.environ.get("MXNET_MEMDUMP_PATH") or None
        if path:
            with atomic_path(path) as tmp:
                with open(tmp, "w") as f:
                    json.dump(doc, f)
        return doc
    except Exception:
        return None


def maybe_oom_report(exc):
    """Engine choke-point hook: report iff ``exc`` smells like device
    memory exhaustion.  Returns True when a report was made."""
    if not is_oom(exc):
        return False
    oom_report(exc)
    return True


register_collector(refresh)


def reset():
    """Test hook: drop tags and the peak watermark."""
    global _peak, _freed_count, _freed_bytes
    with _lock:
        _tags.clear()
        _peak = 0
        _freed_count = 0
        _freed_bytes = 0
