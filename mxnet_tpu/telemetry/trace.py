"""Merge per-process chrome traces into one correlated timeline.

Each process's profiler records event timestamps relative to its own
``perf_counter`` epoch, so two dumps cannot be overlaid as-is.  The
profiler therefore embeds a wall-clock anchor in every dump
(``otherData.wall_t0_us`` = ``time.time()`` at profiler import, i.e.
the wall time of local ``ts == 0``).  :func:`merge_traces` aligns all
inputs to the earliest anchor: an event at local ``ts`` in trace *i*
lands at ``ts + (wall0_i - min_j wall0_j)`` on the merged timeline.

pid layout: server handler spans are recorded at ``pid = rank + 1``
(dist_kvstore.DistServer) and keep that pid verbatim; each input
trace's local events (``pid == 0``) move to a fresh pid above all
server pids so N workers don't collapse onto one track.  The result is
one chrome://tracing / Perfetto file where a worker's ``kv_push`` span
sits directly above the server-side handler span it triggered (both
carry the same ``args.span`` id from the wire meta).
"""
from __future__ import annotations

import json


def _load(t):
    """Accept a path, a full trace dict, or a bare event list."""
    if isinstance(t, str):
        with open(t) as f:
            t = json.load(f)
    if isinstance(t, list):
        t = {"traceEvents": t}
    return t


def merge_traces(traces, out=None, labels=None):
    """Merge chrome traces (paths / dicts / event lists) into one dict.

    ``labels`` optionally names each input (defaults to ``worker<i>``);
    server pids get named ``server<rank>``.  Writes JSON to ``out`` when
    given.  Returns the merged trace dict.
    """
    loaded = [_load(t) for t in traces]
    anchors = [t.get("otherData", {}).get("wall_t0_us") for t in loaded]
    known = [a for a in anchors if a is not None]
    base = min(known) if known else 0.0

    merged = []
    server_pids = set()
    for t in loaded:
        for e in t.get("traceEvents", []):
            pid = e.get("pid", 0)
            if pid != 0:
                server_pids.add(pid)
    next_pid = max(server_pids) + 1 if server_pids else 1

    pid_names = {}
    for i, t in enumerate(loaded):
        shift = (anchors[i] - base) if anchors[i] is not None else 0.0
        local_pid = next_pid
        next_pid += 1
        pid_names[local_pid] = (labels[i] if labels and i < len(labels)
                                else "worker%d" % i)
        for e in t.get("traceEvents", []):
            e = dict(e)
            if "ts" in e:
                e["ts"] = e["ts"] + shift
            pid = e.get("pid", 0)
            if pid == 0:
                e["pid"] = local_pid
            merged.append(e)

    for pid in sorted(server_pids):
        # dist servers record handler spans at pid = requesting worker's
        # rank + 1 (dist_kvstore.DistServer._prof_span)
        pid_names.setdefault(pid, "server:rank%d" % (pid - 1))
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": name}}
            for pid, name in sorted(pid_names.items())]

    result = {"traceEvents": meta + merged, "displayTimeUnit": "ms"}
    if out:
        with open(out, "w") as f:
            json.dump(result, f)
    return result
