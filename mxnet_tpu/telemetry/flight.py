"""Flight recorder: a lock-free per-process event ring for black-box
forensics.

The async engine means the Python stack trace at crash time describes
almost nothing about what the framework was executing — the op that
failed was pushed long before the exception surfaces, and a serving
request's life spans queue, prefill and dozens of decode flushes.  The
flight recorder keeps the last N framework events (engine
push/flush/sync, kvstore RPCs, fault injections, serve scheduler
transitions, memory tags, elastic-membership transitions —
``membership.evict`` / ``membership.join`` / ``membership.epoch`` /
``membership.resync``, each eviction naming the lost rank's last RPC)
in a preallocated ring and dumps them to disk when the process dies, so
a post-mortem can read what *actually* happened instead of where the
exception happened to surface.

Design constraints:

- **Lock-free recording.**  ``record()`` is called from the engine hot
  path and from every HTTP/scheduler thread; it must never contend.
  Sequence numbers come from :func:`itertools.count` (atomic under the
  GIL) and each event writes exactly one ring slot — two racing events
  can at worst overwrite each other's slot near the wrap boundary,
  never corrupt the structure.
- **Bounded memory.**  The ring is a preallocated list (capacity
  rounded up to a power of two so the slot index is a mask, default
  4096 via ``MXNET_FLIGHT_RECORDER_SIZE``); old events are overwritten,
  the ``dropped`` count in :func:`status` says how many.
- **Timeline-compatible anchors.**  Events carry a monotonic timestamp
  relative to module import plus a wall anchor (``wall_t0_us``, the
  wall time of local ``ts == 0`` — the same convention as the profiler
  dumps), so ``tools/mxflight.py merge`` can overlay multi-rank flight
  dumps onto the PR 5 trace timeline via
  :func:`telemetry.merge_traces`.

Crash dumps are **armed** by ``MXNET_FLIGHT_DUMP=<path>`` (``{pid}`` /
``{rank}`` substitute), or programmatically via :func:`arm`.  Arming
installs an ``excepthook`` chain and a chained SIGTERM handler; the
engine additionally calls :func:`crash_dump` when it poisons a var.
Nothing is installed when unarmed — SIGTERM disposition stays whatever
the application set (``CheckpointHandler`` relies on ``SIG_DFL``).
"""
from __future__ import annotations

import itertools
import json
import os
import signal
import sys
import threading
import time

from ..base import atomic_path, env_flag

__all__ = [
    "record", "events", "status", "dump", "load", "enabled", "enable",
    "disable", "arm", "armed", "crash_dump", "reset", "to_trace",
]

_FORMAT_VERSION = 1

_ENABLED = env_flag("MXNET_FLIGHT_RECORDER", True)


def _pow2(n):
    c = 1
    while c < n:
        c <<= 1
    return c


def _capacity_from_env():
    raw = os.environ.get("MXNET_FLIGHT_RECORDER_SIZE") or "4096"
    try:
        n = int(raw)
    except ValueError:
        n = 4096
    return _pow2(max(64, n))


_CAPACITY = _capacity_from_env()
_MASK = _CAPACITY - 1
_ring = [None] * _CAPACITY
_seq = itertools.count()

# wall time of local ts==0 (module import) — same anchor convention as
# profiler dumps, so flight timelines merge with profiler timelines
_WALL_T0 = time.time()
_MONO_T0 = time.monotonic()

_armed_path = os.environ.get("MXNET_FLIGHT_DUMP") or None
_res_token = None   # rescheck handle for the armed dump registration
_hooks_installed = False
_crash_lock = threading.Lock()
_in_crash = False


def enabled():
    return _ENABLED


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    """Stop recording (the ring keeps its contents).  ``bench.py``'s
    ``_notelemetry`` runner toggles this together with the metrics
    registry to measure the observability overhead."""
    global _ENABLED
    _ENABLED = False


def record(kind, **fields):
    """Append one event; returns its sequence number (-1 when disabled).

    ``kind`` is a dotted family name (``engine.push``, ``kv.send``,
    ``serve.admit``, ``fault``, ...); ``fields`` must be JSON-scalar
    values.  Lock-free: one counter increment + one slot store.
    """
    if not _ENABLED:
        return -1
    i = next(_seq)
    _ring[i & _MASK] = (i, time.monotonic() - _MONO_T0, kind, fields)
    return i


def events(kind=None, last=None):
    """Snapshot the ring as a seq-ordered list of event dicts.

    ``kind`` filters by exact name or dotted prefix (``"kv"`` matches
    ``kv.send``/``kv.recv``/...); ``last`` keeps only the N most recent
    after filtering.
    """
    evs = [e for e in list(_ring) if e is not None]
    evs.sort(key=lambda e: e[0])
    out = []
    for seq, ts, k, fields in evs:
        if kind is not None and k != kind and not k.startswith(kind + "."):
            continue
        d = {"seq": seq, "ts": round(ts, 6), "kind": k}
        d.update(fields)
        out.append(d)
    if last is not None:
        out = out[-int(last):]
    return out


def _recorded():
    live = [e[0] for e in list(_ring) if e is not None]
    return (max(live) + 1) if live else 0


def status():
    """Health summary for ``/healthz`` and dump metadata."""
    n = _recorded()
    return {
        "enabled": _ENABLED,
        "capacity": _CAPACITY,
        "recorded": n,
        "dropped": max(0, n - _CAPACITY),
        "armed": _armed_path is not None,
    }


def _rank():
    try:
        return int(os.environ.get("DMLC_RANK", "0") or 0)
    except ValueError:
        return 0


def _expand(path):
    return (path.replace("{pid}", str(os.getpid()))
                .replace("{rank}", str(_rank())))


def dump(path=None, reason="explicit"):
    """Write the ring to ``path`` (default: the armed ``MXNET_FLIGHT_DUMP``
    target) as JSON via ``base.atomic_path``.  Returns the path written."""
    if path is None:
        if _armed_path is None:
            raise ValueError(
                "flight.dump() needs a path (or set MXNET_FLIGHT_DUMP)")
        path = _armed_path
    path = _expand(os.fspath(path))
    st = status()
    doc = {
        "meta": {
            "version": _FORMAT_VERSION,
            "pid": os.getpid(),
            "rank": _rank(),
            "role": os.environ.get("DMLC_ROLE"),
            "reason": reason,
            "wall_t0_us": _WALL_T0 * 1e6,
            "capacity": st["capacity"],
            "recorded": st["recorded"],
            "dropped": st["dropped"],
        },
        "events": events(),
    }
    with atomic_path(path) as tmp:
        with open(tmp, "w") as f:
            json.dump(doc, f)
    return path


def load(path):
    """Parse a flight dump; raises on files that are not flight dumps."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "meta" not in doc or "events" not in doc:
        raise ValueError("%s: not a flight-recorder dump" % (path,))
    return doc


def to_trace(doc, pid=0):
    """Convert a loaded dump into a chrome-trace dict (µs timestamps)
    carrying the dump's wall anchor — directly mergeable with profiler
    dumps via :func:`telemetry.merge_traces`.

    Events are instants by default; an event carrying ``dur_s`` (the
    fleet router's attempt/request spans) renders as a complete "X"
    span ending at its record time.  Span events land on one thread row
    per ``replica`` field (row 0 = the router itself), so a hedged
    request is visible as overlapping spans on two replica rows."""
    evs = []
    tids = {"": 0}   # replica name -> chrome tid (row per replica)
    for e in doc.get("events", []):
        args = {k: v for k, v in e.items() if k not in ("ts", "kind")}
        ts_us = float(e.get("ts", 0.0)) * 1e6
        dur_s = e.get("dur_s")
        if isinstance(dur_s, (int, float)) and dur_s > 0:
            tid = tids.setdefault(str(e.get("replica", "")), len(tids))
            evs.append({"name": e.get("kind", "?"), "ph": "X",
                        "ts": ts_us - float(dur_s) * 1e6,
                        "dur": float(dur_s) * 1e6,
                        "pid": pid, "tid": tid, "args": args})
        else:
            evs.append({"name": e.get("kind", "?"), "ph": "i", "s": "p",
                        "ts": ts_us, "pid": pid, "tid": 0, "args": args})
    other = {}
    anchor = doc.get("meta", {}).get("wall_t0_us")
    if anchor is not None:
        other["wall_t0_us"] = anchor
    return {"traceEvents": evs, "displayTimeUnit": "ms", "otherData": other}


# ----------------------------------------------------------------------
# crash dumps
# ----------------------------------------------------------------------
def armed():
    return _armed_path


def arm(path):
    """Arm crash dumps to ``path`` and install the exception/SIGTERM
    hooks (idempotent).  ``MXNET_FLIGHT_DUMP`` does this at import."""
    global _armed_path, _res_token
    _armed_path = os.fspath(path)
    _install_crash_hooks()
    if _res_token is None:
        try:
            # lazy (testing imports this module); exempt from quiescence
            # — a dump hook legitimately outlives every drain, but a
            # second registration still trips double-free detection
            from ..testing import rescheck as _rescheck
            _res_token = _rescheck.acquire("flight", _armed_path,
                                           exempt=True)
        except ImportError:  # mid-bootstrap arm during circular import
            pass
    return _armed_path


def crash_dump(reason):
    """Best-effort dump to the armed path; no-op (returns None) when
    unarmed.  Called from the excepthook/SIGTERM chains and from the
    engine when a var is poisoned — must never raise or re-enter."""
    global _in_crash
    if _armed_path is None:
        return None
    with _crash_lock:
        if _in_crash:
            return None
        _in_crash = True
    try:
        return dump(reason=reason)
    except Exception:
        return None
    finally:
        _in_crash = False


def _install_crash_hooks():
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    prev_hook = sys.excepthook

    def _flight_excepthook(tp, val, tb):
        crash_dump("exception:%s" % getattr(tp, "__name__", tp))
        prev_hook(tp, val, tb)

    sys.excepthook = _flight_excepthook

    try:
        prev_term = signal.getsignal(signal.SIGTERM)

        def _flight_sigterm(signum, frame):
            crash_dump("sigterm")
            if callable(prev_term):
                prev_term(signum, frame)
            elif prev_term != signal.SIG_IGN:
                # re-raise with default disposition so exit status stays
                # "killed by SIGTERM" for the parent
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _flight_sigterm)
    except (ValueError, OSError):
        pass  # not the main thread / restricted env: excepthook still works


if _armed_path is not None:
    _install_crash_hooks()


def reset():
    """Test hook: clear the ring and restart sequence numbering."""
    global _ring, _seq
    _ring = [None] * _CAPACITY
    _seq = itertools.count()
