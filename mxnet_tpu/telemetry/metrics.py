"""Process-global metrics registry: counters, gauges, histograms.

The observability counterpart of ``mx.profiler`` (docs/observability.md):
the profiler records *individual events* while it is explicitly running;
metrics collect *aggregates* all the time, cheaply enough to stay on in
production — every update is a plain int/dict mutation behind one
``_ENABLED`` branch (``MXNET_TELEMETRY=0`` turns the branch off).

Three primitives, Prometheus-shaped:

* :func:`counter` — monotonically increasing count (``_total`` names).
* :func:`gauge` — point-in-time value (queue depth, samples/sec).
* :func:`histogram` — bucketed distribution with ``sum``/``count``
  (latencies, compile wall-times).

All three take ``**labels``; one (name, labels) pair maps to one metric
object forever, so hot paths resolve their handle once and call
``.inc()``/``.set()``/``.observe()`` directly.

Sources that already aggregate (``Engine.stats``, the ``_jitted`` lru
cache) export through *collectors* — callbacks run at snapshot time that
copy the aggregate into the registry, so the hot path pays nothing.

Export surfaces: :func:`snapshot` (JSON-able dict), :func:`prometheus_text`
(text exposition format), :func:`dump` (atomic file write; also armed at
interpreter exit when ``MXNET_TELEMETRY_DUMP`` is set).
"""
from __future__ import annotations

import atexit
import bisect
import json
import os
import threading
import warnings

from ..base import atomic_path, env_flag

_ENABLED = env_flag("MXNET_TELEMETRY", True)

_lock = threading.Lock()          # guards registration (updates take
                                  # the per-metric lock instead)
_METRICS = {}                     # (name, labels_tuple) -> metric object
_FAMILIES = {}                    # name -> (kind, help)
_COLLECTORS = []                  # snapshot-time exporters

# Histogram default: latency-shaped seconds buckets, 100us..60s
_DEFAULT_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def enabled():
    """Is metric collection on? (``MXNET_TELEMETRY``, default on)."""
    return _ENABLED


def enable():
    """Turn collection on at runtime (e.g. after a disabled baseline)."""
    global _ENABLED
    _ENABLED = True


def disable():
    """Turn collection off at runtime; handles stay valid but updates
    become one dead branch (the overhead bench.py tracks)."""
    global _ENABLED
    _ENABLED = False


class Counter:
    """Monotonic count.  ``set()`` exists for collectors that mirror an
    externally-maintained total (e.g. ``Engine.stats.ops_pushed``).

    Updates take a per-metric lock: ``value += n`` is three bytecodes and
    the serving tier mutates handles from the scheduler loop and every
    HTTP thread at once — without the lock, concurrent increments lose
    counts.  Engine hot-path families are collector-backed (one ``set``
    at snapshot time), so the lock never sits on the dispatch path."""

    __slots__ = ("value", "_lk")

    def __init__(self):
        self.value = 0
        self._lk = threading.Lock()

    def inc(self, n=1):
        if _ENABLED:
            with self._lk:
                self.value += n

    def set(self, value):
        if _ENABLED:
            self.value = value


class Gauge:
    __slots__ = ("value", "_lk")

    def __init__(self):
        self.value = 0
        self._lk = threading.Lock()

    def set(self, value):
        if _ENABLED:
            self.value = value

    def inc(self, n=1):
        if _ENABLED:
            with self._lk:
                self.value += n

    def dec(self, n=1):
        if _ENABLED:
            with self._lk:
                self.value -= n


class Histogram:
    """Prometheus-style histogram: per-bucket counts (cumulated at export
    time), plus ``sum`` and ``count``.  ``observe`` locks so concurrent
    observers can't lose bucket increments (see Counter)."""

    __slots__ = ("bounds", "counts", "sum", "count", "_lk")

    def __init__(self, bounds):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0
        self._lk = threading.Lock()

    def observe(self, value):
        if _ENABLED:
            with self._lk:
                self.counts[bisect.bisect_left(self.bounds, value)] += 1
                self.sum += value
                self.count += 1


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


def _labels_key(labels):
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _check_kind(name, kind):
    fam = _FAMILIES.get(name)
    if fam is not None and fam[0] != kind:
        raise ValueError(
            "metric %r already registered as a %s (requested %s)"
            % (name, fam[0], kind))
    return fam


def _get(kind, name, help, buckets, labels):
    key = (name, _labels_key(labels))
    _check_kind(name, kind)  # before the fast path: a same-key lookup
    m = _METRICS.get(key)    # of the wrong kind must not hand back the
    if m is not None:        # existing series
        return m
    with _lock:
        m = _METRICS.get(key)
        if m is not None:
            return m
        fam = _check_kind(name, kind)
        if fam is None:
            _FAMILIES[name] = (kind, help or "")
        if kind == "histogram":
            m = Histogram(buckets or _DEFAULT_BUCKETS)
        else:
            m = _KINDS[kind]()
        _METRICS[key] = m
        return m


def counter(name, help="", **labels):
    """Resolve (creating if needed) the counter for (name, labels)."""
    return _get("counter", name, help, None, labels)


def gauge(name, help="", **labels):
    return _get("gauge", name, help, None, labels)


def histogram(name, help="", buckets=None, **labels):
    """``buckets`` are upper bounds (exclusive of the implicit +Inf);
    only the first registration of a family sets them."""
    return _get("histogram", name, help, buckets, labels)


def register_collector(fn):
    """Run ``fn()`` before every snapshot/export so sources that already
    aggregate (engine stats, lru caches) publish without hot-path cost."""
    with _lock:
        if fn not in _COLLECTORS:
            _COLLECTORS.append(fn)


def _run_collectors():
    for fn in list(_COLLECTORS):
        try:
            fn()
        except Exception:  # an exporter bug must never break a snapshot
            pass


# -- compile tracking (shared by ops.registry and engine.BulkSegment) -------

_COMPILE_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0, 30.0, 60.0, 120.0, 300.0)
_compile_counts = {}   # signature key -> compiles seen
_retrace_warned = set()


def record_compile(op, key, seconds, n=1):
    """One XLA (re)trace of ``op`` (an op name or ``bulk_segment``) took
    ``seconds``; ``key`` identifies the op *signature* (name + static
    attrs + input fields) the retrace watchdog counts per.

    Warns ONCE per signature when its compile count exceeds
    ``MXNET_RETRACE_WARN_THRESHOLD`` (default 8) — the silent-retrace
    storm (shape/attr churn re-tracing the same op every step) that is
    otherwise invisible until a job is mysteriously slow.
    """
    if not _ENABLED:
        return
    histogram("mxnet_compile_seconds",
              help="XLA compile (trace-to-executable) wall time",
              buckets=_COMPILE_BUCKETS, op=op).observe(seconds)
    counter("mxnet_compiles_total", help="XLA compiles", op=op).inc(n)
    seen = _compile_counts.get(key, 0) + n
    _compile_counts[key] = seen
    threshold = int(os.environ.get("MXNET_RETRACE_WARN_THRESHOLD", "8"))
    if seen > threshold and key not in _retrace_warned:
        _retrace_warned.add(key)
        warnings.warn(
            "op signature %r has compiled %d times "
            "(MXNET_RETRACE_WARN_THRESHOLD=%d): inputs keep changing "
            "shape/dtype or attrs churn, so XLA re-traces instead of "
            "reusing the cached executable — pad/bucket input shapes or "
            "hoist varying attrs; see docs/observability.md"
            % (op, seen, threshold), stacklevel=2)


# -- export -----------------------------------------------------------------

def snapshot():
    """All metrics as one JSON-able dict:
    ``{family: {"type", "help", "series": [{"labels", ...values}]}}``.
    Histogram buckets are cumulative, keyed by upper bound, with the
    implicit ``+Inf`` bucket equal to ``count`` (Prometheus semantics).
    """
    _run_collectors()
    with _lock:
        items = sorted(_METRICS.items())
        fams = dict(_FAMILIES)
    out = {}
    for (name, labels), m in items:
        kind, help_ = fams.get(name, ("counter", ""))
        fam = out.setdefault(name, {"type": kind, "help": help_,
                                    "series": []})
        entry = {"labels": dict(labels)}
        if isinstance(m, Histogram):
            acc, buckets = 0, {}
            for bound, c in zip(m.bounds, m.counts):
                acc += c
                buckets["%g" % bound] = acc
            buckets["+Inf"] = m.count
            entry.update(buckets=buckets, sum=m.sum, count=m.count)
        else:
            entry["value"] = m.value
        fam["series"].append(entry)
    return out


def histogram_quantile(series, q):
    """Estimate the ``q``-quantile (0..1) from one snapshot histogram
    series (Prometheus ``histogram_quantile`` semantics: cumulative
    buckets, linear interpolation within the winning bucket, +Inf
    clamped to the largest finite bound).

    ``series`` is one entry of ``snapshot()[family]["series"]`` — the
    shape the telemetry dump stores, so serving dashboards and the
    serve-smoke CI job can read p50/p99 TTFT straight off a dump without
    the process that produced it.  Returns 0.0 for an empty histogram.
    """
    total = series.get("count", 0)
    if not total:
        return 0.0
    rank = q * total
    prev_bound, prev_acc = 0.0, 0
    finite = [(float(b), c) for b, c in series["buckets"].items()
              if b != "+Inf"]
    finite.sort()
    for bound, acc in finite:
        if acc >= rank:
            span = acc - prev_acc
            frac = (rank - prev_acc) / span if span else 1.0
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_acc = bound, acc
    return finite[-1][0] if finite else 0.0


def _escape_label_value(v):
    """Exposition-format label-value escaping: exactly backslash, double
    quote and newline get escape sequences; every other byte (tabs,
    non-ASCII UTF-8) passes through raw.  ``json.dumps`` is NOT a valid
    substitute — it emits ``\\t``/``\\uXXXX`` sequences the Prometheus
    parser rejects."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                  .replace("\n", "\\n"))


def _fmt_labels(labels, extra=None):
    parts = ['%s="%s"' % (k, _escape_label_value(v))
             for k, v in labels.items()]
    if extra:
        parts.append("%s=%s" % extra)
    return "{%s}" % ",".join(parts) if parts else ""


def render_text(snap):
    """Render one :func:`snapshot`-shaped dict (this registry's or a
    fleet-merged one from ``telemetry.aggregate``) as Prometheus text."""
    lines = []
    for name, fam in snap.items():
        if fam["help"]:
            lines.append("# HELP %s %s" % (name, fam["help"]))
        lines.append("# TYPE %s %s" % (name, fam["type"]))
        for s in fam["series"]:
            labels = s["labels"]
            if fam["type"] == "histogram":
                for bound, c in s["buckets"].items():
                    lines.append("%s_bucket%s %d" % (
                        name, _fmt_labels(labels, ("le", '"%s"' % bound)),
                        c))
                lines.append("%s_sum%s %g"
                             % (name, _fmt_labels(labels), s["sum"]))
                lines.append("%s_count%s %d"
                             % (name, _fmt_labels(labels), s["count"]))
            else:
                lines.append("%s%s %g"
                             % (name, _fmt_labels(labels), s["value"]))
    return "\n".join(lines) + "\n"


def prometheus_text():
    """Prometheus text exposition format (scrape-able / pushgateway-able)."""
    return render_text(snapshot())


def dump(path=None):
    """Atomically write the snapshot to ``path`` (default:
    ``MXNET_TELEMETRY_DUMP`` or ``telemetry.json``).  A ``.prom``/
    ``.txt`` suffix writes Prometheus text; anything else JSON."""
    path = path or os.environ.get("MXNET_TELEMETRY_DUMP") \
        or "telemetry.json"
    if path.endswith((".prom", ".txt")):
        payload = prometheus_text()
    else:
        payload = json.dumps(snapshot(), indent=1, sort_keys=True)
    with atomic_path(path) as tmp:
        with open(tmp, "w") as f:
            f.write(payload)
    return path


def reset():
    """Zero every metric IN PLACE (handles cached by hot paths stay
    valid) and clear the retrace watchdog.  Test isolation helper."""
    with _lock:
        for m in _METRICS.values():
            if isinstance(m, Histogram):
                m.counts = [0] * (len(m.bounds) + 1)
                m.sum = 0.0
                m.count = 0
            else:
                m.value = 0
        _compile_counts.clear()
        _retrace_warned.clear()


def _atexit_dump():
    try:
        dump(os.environ["MXNET_TELEMETRY_DUMP"])
    except Exception as exc:
        # never turn interpreter exit into a traceback — but a silently
        # missing dump file costs hours; leave one line of evidence
        import sys
        print("mxnet_tpu: telemetry dump failed: %s" % (exc,),
              file=sys.stderr)


if os.environ.get("MXNET_TELEMETRY_DUMP"):
    atexit.register(_atexit_dump)
