"""Module — symbol + one GSPMD executor + optimizer.

Capability parity: ``python/mxnet/module/module.py:40`` (bind:364,
forward:575, backward:629, update:646).  The reference drives one
GraphExecutor per GPU and aggregates gradients through a KVStore; here
the executor is a single XLA program (optionally GSPMD-sharded over a
mesh — the all-reduce rides ICI inside the executable) and ``update()``
applies the optimizer through the same KVStore API or a local updater.
"""
from __future__ import annotations

import logging

import jax.numpy as jnp

from .. import ndarray as nd
from .. import optimizer as opt_mod
from ..base import MXNetError
from ..context import cpu, current_context
from ..initializer import Uniform, InitDesc
from ..model import save_checkpoint, load_checkpoint
from ..ndarray.ndarray import NDArray
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup


class Module(BaseModule):
    """Intermediate+high-level module over one sharded executor.

    Parameters
    ----------
    symbol : Symbol
    data_names, label_names : list of str
    context : Context or list of Context (API parity)
    mesh : optional jax.sharding.Mesh for multi-chip data parallel
    """

    def __init__(self, symbol, data_names=('data',),
                 label_names=('softmax_label',), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None, mesh=None, data_axis='data'):
        super().__init__(logger=logger)
        if context is None:
            context = current_context()
        if not isinstance(context, (list, tuple)):
            context = [context]
        self._context = list(context)
        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        self._data_names = data_names
        self._label_names = label_names
        self._fixed_param_names = list(fixed_param_names or [])
        self._state_names = list(state_names or [])
        self._mesh = mesh
        self._data_axis = data_axis

        arg_names = symbol.list_arguments()
        input_names = set(data_names) | set(label_names) | \
            set(self._state_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._exec_group = None
        self._grad_req = None
        self._data_shapes = None
        self._label_shapes = None

    # ------------------------------------------------------------------
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Creates a model from a previously saved checkpoint."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = '%s-%04d.states' % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        """Saves current progress to checkpoint."""
        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, *self.get_params())
        if save_optimizer_states:
            self.save_optimizer_states('%s-%04d.states' % (prefix, epoch))

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._exec_group.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._exec_group.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [(n, tuple(o.shape)) for n, o in
                zip(self._output_names, self._exec_group.get_outputs())] \
            if self._exec_group._exec.outputs else \
            list(zip(self._output_names, [()] * len(self._output_names)))

    # ------------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, 'call bind before initializing the parameters'
        if initializer is None and not (arg_params or aux_params):
            initializer = Uniform(0.01)

        if self._arg_params is None:
            self._arg_params = {
                n: nd.zeros(self._exec_group._exec.arg_dict[n].shape,
                            dtype=self._exec_group._exec.arg_dict[n].dtype)
                for n in self._param_names}
        if self._aux_params is None:
            self._aux_params = {
                n: nd.zeros(self._exec_group._exec.aux_dict[n].shape,
                            dtype=self._exec_group._exec.aux_dict[n].dtype)
                for n in self._aux_names}

        var_attrs = {node.name: node.attrs
                     for node in self._symbol._topo_nodes()
                     if node.is_variable and node.attrs}

        def _fill(name, arr):
            # the framework's initializer protocol is functional:
            # init(desc, shape, dtype) -> array.  Passing the variable's
            # attrs lets Initializer.__call__ honor a per-variable
            # __init__ (sym.var(init=...)) via create()._init_impl —
            # the reference's per-variable init contract, bypassing the
            # bias/gamma suffix dispatch exactly like the reference.
            desc = InitDesc(name, attrs=var_attrs.get(name))
            arr._set_data(jnp.asarray(initializer(
                desc, tuple(arr.shape), arr.data().dtype)))

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    if tuple(cache_arr.shape) != tuple(arr.shape):
                        raise MXNetError(
                            "shape mismatch for %s: %s vs %s" %
                            (name, cache_arr.shape, arr.shape))
                    cache_arr.copyto(arr)
            else:
                if not allow_missing:
                    raise RuntimeError(
                        "%s is not presented" % name)
                if initializer is not None:
                    _fill(name, arr)

        for name, arr in sorted(self._arg_params.items()):
            if arg_params is not None or aux_params is not None:
                _impl(name, arr, arg_params)
            elif initializer is not None:
                _fill(name, arr)
        for name, arr in sorted(self._aux_params.items()):
            if arg_params is not None or aux_params is not None:
                _impl(name, arr, aux_params)
            elif initializer is not None:
                _fill(name, arr)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=allow_extra)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init,
                             allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            return
        self._exec_group.set_params(arg_params, aux_params,
                                    allow_extra=allow_extra)
        self._params_dirty = True
        self.params_initialized = True

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req='write'):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning('Already bound, ignoring bind()')
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        if not for_training:
            assert not inputs_need_grad

        shared_group = None
        if shared_module is not None:
            assert shared_module.binded and \
                shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, data_shapes,
            label_shapes or [], self._param_names, for_training,
            inputs_need_grad=inputs_need_grad, shared_group=shared_group,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            mesh=self._mesh, data_axis=self._data_axis)
        self._data_shapes = self._exec_group.data_shapes
        self._label_shapes = self._exec_group.label_shapes
        self.binded = True

        if shared_module is not None and \
                shared_module.params_initialized:
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
            self.params_initialized = True
        elif self.params_initialized:
            # bind() after load(): push loaded params to the executor
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        arg_params, aux_params = None, None
        if self.params_initialized:
            self._sync_params_from_devices()
            arg_params, aux_params = self._arg_params, self._aux_params
        self._reset_bind()
        self.bind(data_shapes, label_shapes,
                  for_training=self.for_training,
                  inputs_need_grad=self.inputs_need_grad,
                  grad_req=self._grad_req or 'write')
        if arg_params is not None:
            self._exec_group.set_params(arg_params, aux_params)

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning(
                'optimizer already initialized, ignoring...')
            return

        from ..kvstore import create as kv_create

        if kvstore is None:
            self._kvstore = None
            self._update_on_kvstore = False
        elif isinstance(kvstore, str):
            self._kvstore = kv_create(kvstore)
            self._update_on_kvstore = 'dist' not in self._kvstore.type
        else:
            self._kvstore = kvstore
            self._update_on_kvstore = True

        if isinstance(optimizer, str):
            idx2name = dict(enumerate(self._exec_group.param_names))
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                # loss heads (SoftmaxOutput normalization='null') emit
                # per-example gradients SUMMED over the batch; the module
                # divides by the GLOBAL batch size, like the reference
                # (module.py:506-518: batch_size *= kv.num_workers under
                # dist-sync) — without it training diverges
                batch_size = self._exec_group.batch_size
                kv = self._kvstore
                if kv is not None and "dist" in str(kv.type) \
                        and "_sync" in str(kv.type):
                    batch_size *= kv.num_workers
                optimizer_params["rescale_grad"] = 1.0 / batch_size
            optimizer = opt_mod.create(
                optimizer, param_idx2name=idx2name, **optimizer_params)
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        if self._kvstore is not None and self._update_on_kvstore:
            self._kvstore.set_optimizer(self._optimizer)
        if self._kvstore is not None:
            for i, name in enumerate(self._exec_group.param_names):
                arr = self._exec_group._exec.arg_dict.get(name)
                if arr is not None:
                    self._kvstore.init(name, arr)
        self.optimizer_initialized = True
        if hasattr(self, '_preload_opt_states') and \
                self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        # adapt to new batch size / shapes on the fly like the reference
        curr = self._exec_group.data_shapes
        new = [(n, tuple(a.shape)) for n, a in
               zip(self._data_names, data_batch.data)]
        if curr != new:
            label_shapes = None
            if getattr(data_batch, 'label', None):
                label_shapes = [
                    (n, tuple(a.shape)) for n, a in
                    zip(self._label_names, data_batch.label)]
            self.reshape(new, label_shapes)
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Updates parameters from the computed gradients.

        kvstore path: push grad / pull updated weight (update_on_kvstore)
        or pull aggregated grad and run the local updater — same decision
        tree as the reference (module.py:646); on one chip both collapse
        to the fused jitted optimizer step.
        """
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        exec_ = self._exec_group._exec
        if self._update_on_kvstore and self._kvstore is not None:
            for name in self._exec_group.param_names:
                grad = exec_.grad_dict.get(name)
                if grad is None:
                    continue
                weight = exec_.arg_dict[name]
                self._kvstore.push(name, grad)
                self._kvstore.pull(name, out=weight)
        else:
            if self._kvstore is not None:
                for name in self._exec_group.param_names:
                    grad = exec_.grad_dict.get(name)
                    if grad is None:
                        continue
                    self._kvstore.push(name, grad)
                    self._kvstore.pull(name, out=grad,
                                       ignore_sparse=False)
            for i, name in enumerate(self._exec_group.param_names):
                grad = exec_.grad_dict.get(name)
                if grad is None:
                    continue
                self._updater(i, grad, exec_.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._exec_group.update_metric(eval_metric, labels, pre_sliced)

    # ------------------------------------------------------------------
    def _sync_params_from_devices(self):
        if not self._params_dirty:
            return
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, 'wb') as fout:
            fout.write(self._updater.get_states() if self._updater
                       else b'')

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, 'rb') as fin:
            if self._updater:
                self._updater.set_states(fin.read())

    def install_monitor(self, mon):
        assert self.binded
        self._exec_group.install_monitor(mon)

    def prepare(self, data_batch, sparse_row_id_fn=None):
        assert self.binded
