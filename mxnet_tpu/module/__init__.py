"""Module API (parity: ``python/mxnet/module/``) — symbolic training.

``Module`` drives one GSPMD-sharded XLA executor; ``BucketingModule``
adds per-bucket executables with shared parameters.
"""
from .base_module import BaseModule  # noqa: F401
from .module import Module  # noqa: F401
from .bucketing_module import BucketingModule  # noqa: F401
