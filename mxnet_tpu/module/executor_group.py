"""DataParallelExecutorGroup — the Module API's execution backend.

Capability parity: ``python/mxnet/module/executor_group.py:144``.  The
reference slices every batch across GPU contexts (``decide_slices:282``)
and holds one ``GraphExecutor`` per device, re-implementing data
parallelism in Python.  TPU-native mechanism: ONE ``Executor`` whose
callables are single XLA programs; when a ``jax.sharding.Mesh`` is
supplied the batch inputs are GSPMD-sharded over the mesh's data axis and
XLA compiles the gradient all-reduce into the same executable — the
slicing, per-device arg copies, and Python-side gradient summing all
disappear into the partitioner.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd


class DataParallelExecutorGroup:
    """One GSPMD-sharded executor presenting the reference group API.

    Parameters
    ----------
    symbol : Symbol
    contexts : list of Context (API parity; placement is mesh-driven)
    data_shapes, label_shapes : list of (name, shape) or DataDesc
    param_names : list of str — which arguments are parameters
    for_training : bool
    grad_req : str/list/dict
    mesh : optional jax.sharding.Mesh for multi-chip data parallelism
    data_axis : mesh axis carrying the batch dimension
    """

    def __init__(self, symbol, contexts, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad=False,
                 shared_group=None, logger=None, fixed_param_names=None,
                 grad_req='write', state_names=None, mesh=None,
                 data_axis='data'):
        self.symbol = symbol
        self.contexts = contexts
        self.param_names = list(param_names)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.mesh = mesh
        self.data_axis = data_axis

        # normalize (name, shape[, dtype…]) DataDescs to (name, shape)
        self.data_names = [d[0] for d in data_shapes]
        self.label_names = [l[0] for l in label_shapes] \
            if label_shapes else []
        self.data_shapes = [(d[0], tuple(d[1])) for d in data_shapes]
        self.label_shapes = [(l[0], tuple(l[1])) for l in label_shapes] \
            if label_shapes else []
        self.batch_size = self.data_shapes[0][1][0]

        arg_names = symbol.list_arguments()
        self.arg_names = arg_names
        self.aux_names = symbol.list_auxiliary_states()
        input_names = set(self.data_names) | set(self.label_names)
        req = {}
        for name in arg_names:
            if name in self.fixed_param_names:
                req[name] = 'null'
            elif name in self.param_names:
                req[name] = grad_req if isinstance(grad_req, str) \
                    else grad_req.get(name, 'write')
            elif name in input_names:
                req[name] = 'write' if (
                    inputs_need_grad and name in self.data_names) \
                    else 'null'
            else:
                req[name] = 'null'
        if not for_training:
            req = {n: 'null' for n in arg_names}
        self._grad_req = req

        shapes = dict(self.data_shapes + self.label_shapes)
        if shared_group is not None:
            # bucketing: share parameter/grad arrays with the master group
            exec_ = self._bind_shared(shared_group, shapes)
        else:
            exec_ = symbol.simple_bind(
                ctx=contexts[0] if contexts else None,
                grad_req=req, **shapes)
        self.execs = [exec_]
        self._exec = exec_

    def _bind_shared(self, shared_group, shapes):
        master = shared_group._exec
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**shapes)
        args = {}
        for name, shape in zip(self.symbol.list_arguments(), arg_shapes):
            if name in master.arg_dict and \
                    tuple(master.arg_dict[name].shape) == tuple(shape):
                args[name] = master.arg_dict[name]
            else:
                args[name] = nd.zeros(shape)
        auxs = {}
        for name, shape in zip(self.symbol.list_auxiliary_states(),
                               aux_shapes):
            if name in master.aux_dict and \
                    tuple(master.aux_dict[name].shape) == tuple(shape):
                auxs[name] = master.aux_dict[name]
            else:
                auxs[name] = nd.zeros(shape)
        args_grad = {n: g for n, g in master.grad_dict.items()
                     if g is not None}
        return self.symbol.bind(
            ctx=self.contexts[0] if self.contexts else None,
            args=args, aux_states=auxs, grad_req=self._grad_req,
            args_grad=args_grad)

    # -- sharding ---------------------------------------------------------
    def _shard_batch(self, arr):
        if self.mesh is None:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(self.data_axis, *([None] * (arr.data().ndim - 1)))
        return NDArray(jax.device_put(
            arr.data(), NamedSharding(self.mesh, spec)))

    # -- parameter plumbing ----------------------------------------------
    def set_params(self, arg_params, aux_params, allow_extra=False):
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        """Copy current (device) params into the given dicts."""
        for name in self.param_names:
            if name in self._exec.arg_dict:
                arg_params[name] = self._exec.arg_dict[name].copy()
        for name in self.aux_names:
            aux_params[name] = self._exec.aux_dict[name].copy()

    # -- execution --------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self.data_names, data_batch.data):
            arr = arr if isinstance(arr, NDArray) else nd.array(arr)
            feed[name] = self._shard_batch(arr)
        if self.label_names and data_batch.label:
            for name, arr in zip(self.label_names, data_batch.label):
                arr = arr if isinstance(arr, NDArray) else nd.array(arr)
                feed[name] = self._shard_batch(arr)
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        if not self.for_training:
            raise MXNetError("re-bind with for_training=True to run backward")
        self._exec.backward(out_grads=out_grads)

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError(
                "bind with inputs_need_grad=True to get input grads")
        return [self._exec.grad_dict[n] for n in self.data_names]

    @property
    def grad_arrays(self):
        return [[self._exec.grad_dict[n]] for n in self.param_names
                if self._exec.grad_dict.get(n) is not None]

    def grad_dict(self):
        return self._exec.grad_dict

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update_dict(
            dict(zip(self.label_names, labels)),
            dict(zip(self.symbol.list_outputs(), self.get_outputs())))

    def install_monitor(self, mon):
        for exe in self.execs:
            exe.set_monitor_callback(mon.tip if hasattr(mon, 'tip')
                                     else mon)
