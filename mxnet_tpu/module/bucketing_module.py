"""BucketingModule — variable-length training with shared parameters.

Capability parity: ``python/mxnet/module/bucketing_module.py:40``.  One
``Module`` per bucket key, all sharing parameter arrays with the default
bucket's module.  TPU-native note: each bucket is its own XLA executable
(static shapes per bucket — exactly the reference's per-seq-length
executor idea, which is also how jit shape-specialization works), while
parameters live in shared NDArrays so no copying happens on switch.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    """Parameters
    ----------
    sym_gen : fn(bucket_key) -> (symbol, data_names, label_names)
    default_bucket_key : the key of the largest bucket (bound first)
    """

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None, mesh=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._context = context
        self._mesh = mesh
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        self._monitor = None
        self._grad_req = None

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    def _gen_symbol(self, key):
        res = self._sym_gen(key)
        if not isinstance(res, tuple):
            return res, ('data',), ('softmax_label',)
        return res

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        return self._gen_symbol(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        return self._gen_symbol(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    # ------------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init,
                             allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            return
        self._curr_module.set_params(arg_params, aux_params,
                                     allow_missing=allow_missing,
                                     force_init=force_init,
                                     allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req='write'):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning('Already bound, ignoring bind()')
            return
        assert shared_module is None, \
            'shared_module for BucketingModule is not supported'

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        self.binded = True

        sym, dnames, lnames = self._gen_symbol(self._default_bucket_key)
        module = Module(sym, dnames, lnames, logger=self.logger,
                        context=self._context,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names, mesh=self._mesh)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Switches to a different bucket, binding it if new."""
        assert self.binded, 'call bind before switching bucket'
        if bucket_key not in self._buckets:
            sym, dnames, lnames = self._gen_symbol(bucket_key)
            module = Module(sym, dnames, lnames, logger=self.logger,
                            context=self._context,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names,
                            mesh=self._mesh)
            module.bind(data_shapes, label_shapes, self._curr_module.
                        for_training, self._curr_module.inputs_need_grad,
                        force_rebind=False,
                        shared_module=self._buckets[
                            self._default_bucket_key],
                        grad_req=self._grad_req)
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            if self.optimizer_initialized:
                module.borrow_optimizer(
                    self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning('optimizer already initialized, ignoring.')
            return
        self._curr_module.init_optimizer(kvstore, optimizer,
                                         optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def prepare(self, data_batch, sparse_row_id_fn=None):
        assert self.binded
        bucket_key = data_batch.bucket_key
        original_bucket_key = self._curr_bucket_key
        data_shapes = [(n, tuple(a.shape)) for n, a in
                       zip(self.data_names, data_batch.data)]
        label_shapes = None
        if getattr(data_batch, 'label', None):
            label_shapes = [
                (n, tuple(a.shape)) for n, a in
                zip(self._curr_module.label_names, data_batch.label)]
        self.switch_bucket(bucket_key, data_shapes, label_shapes)
        self.switch_bucket(original_bucket_key, None, None)

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bucket_key = getattr(data_batch, 'bucket_key',
                             self._default_bucket_key)
        data_shapes = [(n, tuple(a.shape)) for n, a in
                       zip(self.data_names, data_batch.data)]
        label_shapes = None
        if getattr(data_batch, 'label', None):
            label_shapes = [
                (n, tuple(a.shape)) for n, a in
                zip(self._curr_module.label_names, data_batch.label)]
        self.switch_bucket(bucket_key, data_shapes, label_shapes)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        assert self.binded
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_states(merge_multi_context)

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        self._curr_module.set_states(states, value)
