"""Dynamic loss scaler (parity: python/mxnet/contrib/amp/loss_scaler.py).

Classic dynamic scaling: on overflow (non-finite grads) halve the scale
and skip the update; after ``scale_window`` clean steps double it.  With
bfloat16 (the TPU default) scaling is rarely needed — exponent range
matches float32 — but the API is kept for float16 parity.
"""
from __future__ import annotations

import numpy as np


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, min_scale=1.0):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._min_scale = float(min_scale)
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient of ``params`` is non-finite."""
        for p in params:
            if getattr(p, "grad_req", "write") == "null":
                continue
            try:
                g = p.grad() if callable(getattr(p, "grad", None)) else p.grad
            except Exception:
                continue
            if g is None:
                continue
            # dynamic loss scaling must inspect grads on host
            a = g.asnumpy() if hasattr(g, "asnumpy") else np.asarray(g)  # mxlint: allow-host-sync
            if not np.isfinite(a.astype(np.float64)).all():
                return True
        return False

    def update_scale(self, overflow):
        """Adjust the scale; returns True if the step should be SKIPPED."""
        if overflow:
            self.loss_scale = max(self._min_scale,
                                  self.loss_scale / self._scale_factor)
            self._unskipped = 0
            return True
        self._unskipped += 1
        if self._unskipped >= self._scale_window:
            self.loss_scale *= self._scale_factor
            self._unskipped = 0
        return False
