"""AMP op lists (parity: python/mxnet/contrib/amp/lists/symbol.py).

Three classes, mirroring the reference's FP16_FUNCS / FP32_FUNCS /
WIDEST_TYPE_CASTS (``amp.py:161-195``), retargeted at bfloat16 — the
MXU-native low-precision dtype (no loss scaling strictly required, but a
dynamic scaler is provided for float16 parity).
"""

# compute-bound ops that run in the target (low-precision) dtype —
# these are the MXU matmul/conv consumers
TARGET_DTYPE_OPS = [
    "FullyConnected",
    "Convolution",
    "Deconvolution",
    "dot",
    "batch_dot",
    "_linalg_gemm",
    "_linalg_gemm2",
    "RNN",
    "_npi_einsum",
    "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
    # flash attention: bf16 in/out is safe — the Pallas kernel upcasts
    # per-block and accumulates softmax/output in f32 internally; f32
    # inputs would double attention HBM traffic and halve MXU rate
    # (xplane r5: f32[96,512,64] custom-calls before this entry)
    "_contrib_flash_attention",
]

# numerically-sensitive ops forced to float32
FP32_OPS = [
    "softmax",
    "log_softmax",
    "softmin",
    "SoftmaxActivation",
    "SoftmaxOutput",
    "softmax_cross_entropy",
    "CTCLoss",
    "exp",
    "log",
    "log2",
    "log10",
    "log1p",
    "expm1",
    "logsumexp",
    "norm",
    "mean",
    "sum",
    "prod",
    "nansum",
    "nanprod",
    "cumsum",
    "erfinv",
    "gamma",
    "gammaln",
    "rsqrt",
    "rcbrt",
    "reciprocal",
    "_power",
    "broadcast_power",
    "_power_scalar",
    "_rpower_scalar",
    "_rdiv_scalar",
    "smooth_l1",
    "L2Normalization",
    "InstanceNorm",
    "LayerNorm",
    "GroupNorm",
    # measured r5 (tools A/B, llama bench geometry, best-of-3 windows):
    # norms IN this list run 7% faster end-to-end than bf16-in/bf16-out
    # norms (131.7k vs 122.7k tok/s) — XLA fuses the f32 norm chain into
    # the adjacent matmuls and skips a convert round trip
    "RMSNorm",
]

# multi-input ops whose inputs are cast to the widest participating dtype
WIDEST_TYPE_CASTS = [
    "elemwise_add",
    "elemwise_sub",
    "elemwise_mul",
    "elemwise_div",
    "broadcast_add",
    "broadcast_sub",
    "broadcast_mul",
    "broadcast_div",
    "broadcast_maximum",
    "broadcast_minimum",
    "broadcast_hypot",
    "_maximum",
    "_minimum",
    "_hypot",
    "concat",
    "stack",
    "where",
]
