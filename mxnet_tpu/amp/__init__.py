"""AMP — automatic mixed precision (parity: python/mxnet/contrib/amp/amp.py).

Reference mechanism: a graph pass (``low_precision_pass.cc``) rewrites the
symbol with ``amp_cast``/``amp_multicast`` around ops according to
allow/deny lists, plus a dynamic ``LossScaler`` folded into backward.

TPU-native mechanism: one dispatch-time dtype rewrite at the op registry
choke point (``ops/registry._prep``) — every op invocation, imperative OR
inside a ``hybridize()``/``JitTrainStep`` trace, passes through it, so a
single hook covers both execution modes (no graph rewrite needed; XLA
fuses the inserted converts for free).  Target dtype defaults to
bfloat16, the MXU-native type; float16 + dynamic loss scaling is kept
for parity.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..base import MXNetError
from . import lists
from .loss_scaler import LossScaler

_state = {
    "active": False,
    "target_dtype": None,
    "target_ops": frozenset(),
    "fp32_ops": frozenset(),
    "widest_ops": frozenset(),
}

_LOW = (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16))


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP process-wide (parity: amp.py:161).

    target_precision_ops / fp32_ops extend the default lists;
    conditional_fp32_ops is accepted for API parity (treated as fp32).
    """
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("AMP target_dtype must be bfloat16 or float16")
    target = set(lists.TARGET_DTYPE_OPS) | set(target_precision_ops or ())
    fp32 = set(lists.FP32_OPS) | set(fp32_ops or ())
    if conditional_fp32_ops:
        fp32 |= {op for op, _, _ in conditional_fp32_ops} \
            if isinstance(next(iter(conditional_fp32_ops)), tuple) \
            else set(conditional_fp32_ops)
    _state.update(
        active=True,
        target_dtype=jnp.dtype(target_dtype),
        target_ops=frozenset(target),
        fp32_ops=frozenset(fp32),
        widest_ops=frozenset(lists.WIDEST_TYPE_CASTS),
    )


def turn_off():
    _state["active"] = False


def is_active():
    return _state["active"]


def transform_inputs(op_name, datas):
    """Dispatch-time dtype rewrite; called from ops/registry._prep."""
    if not _state["active"]:
        return datas
    if op_name in _state["target_ops"]:
        tgt = _state["target_dtype"]
        return tuple(
            d.astype(tgt)
            if hasattr(d, "dtype") and d.dtype in (jnp.float32,) + _LOW
            and d.dtype != tgt else d
            for d in datas)
    if op_name in _state["fp32_ops"]:
        return tuple(
            d.astype(jnp.float32)
            if hasattr(d, "dtype") and d.dtype in _LOW else d
            for d in datas)
    if op_name in _state["widest_ops"]:
        fl = [d.dtype for d in datas
              if hasattr(d, "dtype")
              and d.dtype in (jnp.dtype(jnp.float32),) + _LOW]
        if len(set(fl)) > 1:
            widest = jnp.dtype(jnp.float32) if jnp.dtype(jnp.float32) in fl \
                else fl[0]
            return tuple(
                d.astype(widest)
                if hasattr(d, "dtype") and d.dtype in _LOW + (
                    jnp.dtype(jnp.float32),) and d.dtype != widest else d
                for d in datas)
    return datas


def convert_hybrid_block(net, target_dtype="bfloat16"):
    """Cast a Gluon block's parameters for AMP execution (parity:
    amp.convert_hybrid_block).  Also enables AMP if not yet active."""
    if not _state["active"]:
        init(target_dtype)
    net.cast(target_dtype)
    return net


def init_trainer(trainer):
    """Attach a dynamic LossScaler to a Trainer (parity: amp.py:305).

    Wraps ``trainer.step`` so overflowed iterations are skipped and the
    scale adapts.
    """
    if getattr(trainer, "_amp_loss_scaler", None) is not None:
        return
    scaler = LossScaler()
    trainer._amp_loss_scaler = scaler
    orig_step = trainer.step

    def step(batch_size, ignore_stale_grad=False):
        params = [p for p in trainer._params]
        overflow = scaler.has_overflow(params)
        skip = scaler.update_scale(overflow)
        if skip:
            for p in params:
                if p.grad_req != "null":
                    p.zero_grad()
            return
        orig_step(batch_size, ignore_stale_grad=ignore_stale_grad)

    trainer.step = step


def unscale(trainer):
    """Divide current grads by the loss scale (parity: amp.py:406)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req == "null" or p._data is None:
            continue
        raw = p._data._grad
        if raw is None:
            continue
        # write the raw grad buffer (Parameter.grad() returns a fresh
        # wrapper; mutating it would be a no-op)
        p._data._grad = raw * inv
    # grads are now unscaled — undo the 1/loss_scale folded into step()
    if hasattr(trainer, "_amp_orig_scale"):
        trainer._scale = trainer._amp_orig_scale


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Scale the loss and fold 1/scale into the optimizer's rescale_grad
    (parity: amp.py:380)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        init_trainer(trainer)
        scaler = trainer._amp_loss_scaler
    if not hasattr(trainer, "_amp_orig_scale"):
        trainer._amp_orig_scale = trainer._scale
    # step() divides by batch_size on top of _scale, so folding 1/loss_scale
    # into _scale makes grads come out unscaled after the update
    trainer._scale = trainer._amp_orig_scale / scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale
