"""Multi-host GSPMD runtime (SURVEY §5.8 distributed backend tier).

The reference scales across machines with ps-lite/NCCL processes; the
TPU-native equivalent is ONE logical XLA program spanning every host's
chips: each process calls :func:`init_multihost`, the global mesh sees
all devices, and ``pjit``-compiled steps insert ICI collectives within a
host and DCN collectives across hosts automatically (scaling-book recipe).

The process/rendezvous contract is the SAME DMLC_* environment the
parameter-server tier and ``tools/launch.py`` already use, so
``tools/launch.py --backend gspmd -n 4 --launcher ssh -H hosts``
launches either tier:

* ``DMLC_PS_ROOT_URI`` / ``DMLC_PS_ROOT_PORT`` → the jax.distributed
  coordinator (rank-0 host).
* ``DMLC_NUM_WORKER`` / ``DMLC_RANK`` → process count / id.

On real pods each process owns its host's chips; in tests the same code
runs as N processes × K virtual CPU devices (gloo collectives).
"""
from __future__ import annotations

import os

import numpy as np

import jax

_initialized = [False]


def init_multihost(coordinator=None, num_processes=None, process_id=None):
    """Join (or create) the multi-process JAX runtime.

    Arguments default from the DMLC env contract.  Safe to call once per
    process, before any backend use.  Returns (num_processes, process_id).
    """
    if _initialized[0]:
        return (jax.process_count(), jax.process_index())
    if num_processes is None:
        num_processes = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if num_processes <= 1:
        _initialized[0] = True
        return (1, 0)
    if process_id is None:
        process_id = int(os.environ.get("DMLC_RANK", "0"))
    if coordinator is None:
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "29400"))
        coordinator = "%s:%d" % (host, port)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized[0] = True
    return (num_processes, process_id)


def global_mesh(axes=None):
    """A mesh over EVERY process's devices (call after init_multihost)."""
    from .mesh import make_mesh

    return make_mesh(axes, devices=jax.devices())


def host_local_to_global(array, mesh, spec):
    """Assemble per-process host-local shards into one global array.

    Each process passes ITS slice of the batch (e.g. the rows its data
    pipeline loaded); the result is a global array laid out by ``spec``
    over ``mesh`` that pjit-compiled steps consume directly — the
    multi-host analogue of the reference feeding each worker its own
    data shard.
    """
    from jax.experimental import multihost_utils

    from ..ndarray.ndarray import NDArray
    from .. import sharding as _sharding

    if isinstance(array, NDArray):
        array = array.data()
    return multihost_utils.host_local_array_to_global_array(
        np.asarray(array), _sharding.as_jax_mesh(mesh), spec)


def global_to_host_local(array, mesh, spec):
    """Inverse of :func:`host_local_to_global` (fetch this host's rows)."""
    from jax.experimental import multihost_utils

    from .. import sharding as _sharding

    return multihost_utils.global_array_to_host_local_array(
        array, _sharding.as_jax_mesh(mesh), spec)


def sync_global_devices(tag="barrier"):
    """Cross-process barrier (reference kvstore barrier analogue)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)
