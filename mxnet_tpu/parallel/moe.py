"""Expert parallelism: mixture-of-experts FFN sharded over a mesh axis.

No reference counterpart (SURVEY §2.4 lists EP/MoE as absent) — designed
TPU-first in the GShard/Switch mold: top-1 token routing with a capacity
factor, dense einsum dispatch/combine (XLA-friendly — no dynamic
shapes), experts laid out along an ``expert`` mesh axis so each device
holds ``E / ep`` expert FFNs.  Inside ``shard_map`` the dispatch einsum
contracts the LOCAL expert slice only; the final combine ``psum``s
partial outputs over the axis — the all-to-all of classic MoE expressed
as (replicated tokens × sharded experts), which XLA lowers to ICI
collectives under jit.

Because routing is a straight-through top-1 (gate value scales the
expert output), the whole layer is differentiable; dropped tokens
(capacity overflow) contribute zero output and zero gradient, exactly
like Switch Transformer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import MXNetError

__all__ = ["moe_ffn", "moe_ffn_sharded", "router_top1"]


def router_top1(x, router_w, n_experts, capacity):
    """Top-1 routing: returns (dispatch (S,E,C), combine (S,E,C), aux_loss).

    ``dispatch`` is a 0/1 mask placing each kept token into an expert
    capacity slot; ``combine`` carries the gate probability in the same
    slot.  ``aux_loss`` is the Switch load-balancing loss
    (E * Σ_e fraction_e * prob_e).
    """
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)          # (S, E)
    expert = jnp.argmax(probs, axis=-1)              # (S,)
    gate = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)
    # position of each token within its expert's queue
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (S, E)
    kept = (pos < capacity) & (onehot > 0)
    slot = pos.astype(jnp.int32)
    slot_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32) \
        * kept[..., None]                            # (S, E, C)
    dispatch = slot_oh
    combine = dispatch * gate[:, None, None]
    # load-balancing auxiliary (Switch eq. 4)
    frac = jnp.mean(onehot, axis=0)
    prob_mean = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac * prob_mean)
    return dispatch, combine, aux


def _expert_ffn(w_in, w_out, h):
    """(E, C, D) tokens through per-expert SwiGLU-free MLPs: gelu MLP."""
    a = jnp.einsum("ecd,edh->ech", h, w_in)
    a = jax.nn.gelu(a)
    return jnp.einsum("ech,ehd->ecd", a, w_out)


def moe_ffn(x, router_w, w_in, w_out, capacity_factor=1.25):
    """Single-device MoE FFN (the semantics oracle for the sharded path).

    x (S, D); router_w (D, E); w_in (E, D, H); w_out (E, H, D).
    Returns (y (S, D), aux_loss).
    """
    s, d = x.shape
    e = router_w.shape[1]
    capacity = max(1, int(capacity_factor * s / e))
    dispatch, combine, aux = router_top1(x, router_w, e, capacity)
    expert_in = jnp.einsum("sec,sd->ecd", dispatch,
                           x.astype(jnp.float32))
    expert_out = _expert_ffn(w_in.astype(jnp.float32),
                             w_out.astype(jnp.float32), expert_in)
    y = jnp.einsum("sec,ecd->sd", combine, expert_out)
    return y.astype(x.dtype), aux


def moe_ffn_sharded(x, router_w, w_in, w_out, mesh, axis_name="expert",
                    capacity_factor=1.25):
    """Expert-parallel MoE FFN over ``axis_name`` of ``mesh``.

    Tokens are replicated along the expert axis; the expert weight
    tables (E, ...) are sharded so each device runs only its local
    E/ep experts, and partial outputs are ``psum``-combined.  Numerics
    match :func:`moe_ffn` exactly (same routing, same capacity).
    """
    from ..analysis.collective_check import check_axis
    from .. import sharding as _sharding

    mesh = _sharding.as_jax_mesh(mesh)
    check_axis(mesh, axis_name, op="moe_ffn_sharded")
    ep = mesh.shape[axis_name]
    e = router_w.shape[1]
    if e % ep != 0:
        raise MXNetError("n_experts (%d) must divide the %r axis (%d)"
                         % (e, axis_name, ep))
    s = x.shape[0]
    capacity = max(1, int(capacity_factor * s / e))

    def local(xl, rw, wi, wo):
        # routing is computed identically everywhere (replicated inputs,
        # full router table); only the expert compute is sharded
        dispatch, combine, aux = router_top1(xl, rw, e, capacity)
        idx = jax.lax.axis_index(axis_name)
        lo = idx * (e // ep)
        dloc = jax.lax.dynamic_slice_in_dim(dispatch, lo, e // ep, 1)
        cloc = jax.lax.dynamic_slice_in_dim(combine, lo, e // ep, 1)
        expert_in = jnp.einsum("sec,sd->ecd", dloc,
                               xl.astype(jnp.float32))
        expert_out = _expert_ffn(wi.astype(jnp.float32),
                                 wo.astype(jnp.float32), expert_in)
        y = jnp.einsum("sec,ecd->sd", cloc, expert_out)
        return jax.lax.psum(y, axis_name).astype(xl.dtype), aux

    from .mesh import shard_map

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P(axis_name), P(axis_name)),
        out_specs=(P(), P()),
        check_vma=False,
    )(x, router_w, w_in, w_out)
