"""Device-mesh helpers (legacy spellings over the sharding substrate).

The mesh is the TPU-native replacement for the reference's device lists
(``ctx=[mx.gpu(0), mx.gpu(1), ...]`` in ``Module.bind`` /
``Trainer``): axes are named (``data``, ``model``, ``pipe``, ``seq``,
``expert``) and shardings are expressed as ``PartitionSpec`` over those
names; XLA lowers them to ICI/DCN collectives (scaling-book recipe).

Since the GSPMD substrate landed (``mxnet_tpu/sharding/``), that
package owns mesh construction and the ambient-mesh scope; this module
keeps the historical entry points (``make_mesh``, ``current_mesh``,
``MeshScope``, ``shard_params``) as thin delegates so existing callers
and checkpoints of API usage keep working.  New code should prefer
``mx.sharding.Mesh`` — it is the same object underneath.
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import sharding as _sharding


def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable ``shard_map``: newer jax exposes it as
    ``jax.shard_map(..., check_vma=)``, older releases only ship
    ``jax.experimental.shard_map`` with the ``check_rep=`` spelling.

    Accepts a framework ``sharding.Mesh`` or a raw jax mesh."""
    mesh = _sharding.as_jax_mesh(mesh)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def make_mesh(axes=None, devices=None):
    """Create a named jax Mesh.

    ``axes``: dict name->size (-1 once for 'remaining devices'), or None
    for a 1-axis data mesh over all devices.  Returns the raw
    ``jax.sharding.Mesh`` (legacy contract); ``sharding.Mesh`` wraps the
    same constructor.
    """
    return _sharding.Mesh(axes, devices=devices).jax_mesh


def current_mesh():
    """The ambient mesh — one stack shared with ``sharding.current_mesh``
    (so ``with mx.tpu(mesh=...)`` and ``MeshScope`` see each other)."""
    return _sharding.current_mesh()


class MeshScope:
    """``with MeshScope(mesh):`` — sets the ambient mesh for Trainer/KVStore.

    Same stack as ``with sharding.Mesh(...):``; kept for back-compat."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        _sharding.push_mesh(self.mesh)
        return self.mesh

    def __exit__(self, *a):
        _sharding.pop_mesh()


def replicated(mesh):
    return NamedSharding(_sharding.as_jax_mesh(mesh), P())


def data_sharding(mesh, axis="data", ndim=1):
    """Shard dim 0 (batch) over ``axis``, replicate the rest."""
    return NamedSharding(_sharding.as_jax_mesh(mesh),
                         P(axis, *([None] * (ndim - 1))))


def shard_params(mesh, params, rule=None):
    """Device_put parameter arrays with shardings from ``rule``.

    ``rule(name, shape) -> PartitionSpec`` (None → replicate).  This is the
    entry point for tensor parallelism: e.g. megatron-style rules return
    ``P(None, 'model')`` for up-projections.
    """
    jm = _sharding.as_jax_mesh(mesh)
    out = {}
    for name, arr in params.items():
        spec = rule(name, arr.shape) if rule is not None else None
        sh = NamedSharding(jm, spec if spec is not None else P())
        _sharding.maybe_verify(jm, sh.spec, shape=arr.shape,
                               what="shard_params[%s]" % name)
        out[name] = jax.device_put(arr, sh)
    return out
