"""Device-mesh helpers.

The mesh is the TPU-native replacement for the reference's device lists
(``ctx=[mx.gpu(0), mx.gpu(1), ...]`` in ``Module.bind`` /
``Trainer``): axes are named (``data``, ``model``, ``pipe``, ``seq``,
``expert``) and shardings are expressed as ``PartitionSpec`` over those
names; XLA lowers them to ICI/DCN collectives (scaling-book recipe).
"""
from __future__ import annotations

import threading

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_state = threading.local()


def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable ``shard_map``: newer jax exposes it as
    ``jax.shard_map(..., check_vma=)``, older releases only ship
    ``jax.experimental.shard_map`` with the ``check_rep=`` spelling."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def make_mesh(axes=None, devices=None):
    """Create a named Mesh.

    ``axes``: dict name->size (-1 once for 'remaining devices'), or None
    for a 1-axis data mesh over all devices.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    names = list(axes)
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError(
            "mesh %s needs %d devices, have %d" % (axes, total, n))
    arr = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def current_mesh():
    return getattr(_state, "mesh", None)


class MeshScope:
    """``with MeshScope(mesh):`` — sets the ambient mesh for Trainer/KVStore."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        self._prev = getattr(_state, "mesh", None)
        _state.mesh = self.mesh
        return self.mesh

    def __exit__(self, *a):
        _state.mesh = self._prev


def replicated(mesh):
    return NamedSharding(mesh, P())


def data_sharding(mesh, axis="data", ndim=1):
    """Shard dim 0 (batch) over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def shard_params(mesh, params, rule=None):
    """Device_put parameter arrays with shardings from ``rule``.

    ``rule(name, shape) -> PartitionSpec`` (None → replicate).  This is the
    entry point for tensor parallelism: e.g. megatron-style rules return
    ``P(None, 'model')`` for up-projections.
    """
    out = {}
    for name, arr in params.items():
        spec = rule(name, arr.shape) if rule is not None else None
        sh = NamedSharding(mesh, spec if spec is not None else P())
        out[name] = jax.device_put(arr, sh)
    return out
