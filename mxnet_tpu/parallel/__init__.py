"""Parallel training on device meshes.

This package is the TPU-native answer to three reference subsystems at once
(SURVEY.md §2.4):

* ``DataParallelExecutorGroup`` (``python/mxnet/module/executor_group.py:144``)
  — batch slicing across devices → here: a sharded batch axis on a
  ``jax.sharding.Mesh``, XLA inserting the gradient all-reduce over ICI.
* KVStore ``device``/``nccl`` gradient aggregation (``src/kvstore/comm.h:451``)
  — collectives are *compiled into the train-step executable* instead of
  being scheduled as separate engine ops.
* ``ctx_group`` manual model parallelism (``AssignContext``,
  ``src/executor/graph_executor.cc:1043``) — generalized to tensor/pipeline
  sharding rules over named mesh axes.
"""
from ..sharding import Mesh, PartitionSpec, P, as_jax_mesh  # noqa: F401
from .mesh import (  # noqa: F401
    make_mesh, current_mesh, data_sharding, replicated, shard_params,
    MeshScope, shard_map,
)
from .train_step import JitTrainStep  # noqa: F401
from .tp_rules import megatron_rule, pattern_rule  # noqa: F401
from .ring_attention import ring_attention, ring_attention_sharded  # noqa: F401,E501
from .moe import moe_ffn, moe_ffn_sharded  # noqa: F401
from .pipeline import (  # noqa: F401
    gpipe, gpipe_loss_fn, HostPipeline, partition_llama,
)
from .multihost import (  # noqa: F401
    init_multihost, global_mesh, host_local_to_global,
    global_to_host_local, sync_global_devices,
)
