"""Pipeline parallelism: GPipe microbatch schedule over a mesh axis.

No reference counterpart (SURVEY §2.4 lists pipeline parallel as absent
from the reference) — designed TPU-first: one *identical* stage per
device along the ``pp`` axis, activations hopping stage-to-stage via
``lax.ppermute`` while a ``lax.scan`` advances the microbatch clock.
Each tick every device computes its stage on its current activation and
ships the result one hop down the ring — the classic (M + n - 1)-tick
GPipe schedule with bubble fraction (n-1)/(M+n-1).

Because the whole schedule is pure jnp (scan + ppermute), ``jax.grad``
through it yields the reverse pipeline automatically — backward
activations flow the opposite direction with no hand-written schedule.

Uniform stages fit transformer stacks naturally (N identical encoder
cells); combine with a ``data`` mesh axis for dp×pp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError


def gpipe(stage_fn, stacked_params, x_microbatches, mesh, axis_name="pp"):
    """Run ``n_stages`` copies of ``stage_fn`` as a pipeline.

    Parameters
    ----------
    stage_fn : (stage_params, activation) -> activation, shape-preserving
    stacked_params : pytree whose leaves have leading axis ``n_stages``
        (stage i's weights at index i) — sharded over ``axis_name``
    x_microbatches : (M, microbatch, ...) array, replicated
    mesh : jax.sharding.Mesh containing ``axis_name``
    Returns the last stage's outputs, (M, microbatch, ...), replicated.
    """
    n = mesh.shape[axis_name]
    m = x_microbatches.shape[0]
    if n < 2:
        raise MXNetError("gpipe needs a pipeline axis of size >= 2")

    def per_device(params_local, xs):
        # shard_map gives each device a leading-axis slice of size 1
        params = jax.tree_util.tree_map(lambda p: p[0], params_local)
        idx = lax.axis_index(axis_name)
        state0 = jnp.zeros(xs.shape[1:], xs.dtype)
        perm = [(i, i + 1) for i in range(n - 1)]

        def tick(state, t):
            x_t = xs[jnp.clip(t, 0, m - 1)]
            inp = jnp.where(idx == 0, x_t, state)
            out = stage_fn(params, inp)
            nxt = lax.ppermute(out, axis_name, perm)
            return nxt, out

        _, outs = lax.scan(tick, state0, jnp.arange(m + n - 1))
        # the LAST stage's outputs for microbatch j appear at tick
        # j + (n-1); zero on every other device, then psum-replicate
        mine = lax.dynamic_slice_in_dim(outs, n - 1, m, axis=0)
        # select, don't multiply: dead-lane ticks run stage_fn on zero
        # bootstrap state, and 0 * NaN would leak NaN through the psum
        mine = jnp.where(idx == n - 1, mine, jnp.zeros_like(mine))
        return lax.psum(mine, axis_name)

    return jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(axis_name), P()), out_specs=P(),
        check_vma=False,
    )(stacked_params, x_microbatches)


def gpipe_loss_fn(stage_fn, loss_fn, mesh, axis_name="pp"):
    """Compose a differentiable pipelined loss:
    ``f(stacked_params, x_microbatches, y_microbatches) -> scalar``.
    Gradients (via ``jax.grad``) run the reverse pipeline automatically.
    """

    def f(stacked_params, x_mb, y_mb):
        outs = gpipe(stage_fn, stacked_params, x_mb, mesh, axis_name)
        return loss_fn(outs, y_mb)

    return f
