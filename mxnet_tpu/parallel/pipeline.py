"""Pipeline parallelism: GPipe microbatch schedule over a mesh axis.

No reference counterpart (SURVEY §2.4 lists pipeline parallel as absent
from the reference) — designed TPU-first: one *identical* stage per
device along the ``pp`` axis, activations hopping stage-to-stage via
``lax.ppermute`` while a ``lax.scan`` advances the microbatch clock.
Each tick every device computes its stage on its current activation and
ships the result one hop down the ring — the classic (M + n - 1)-tick
GPipe schedule with bubble fraction (n-1)/(M+n-1).

Because the whole schedule is pure jnp (scan + ppermute), ``jax.grad``
through it yields the reverse pipeline automatically — backward
activations flow the opposite direction with no hand-written schedule.

Uniform stages fit transformer stacks naturally (N identical encoder
cells); combine with a ``data`` mesh axis for dp×pp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError


def gpipe(stage_fn, stacked_params, x_microbatches, mesh, axis_name="pp"):
    """Run ``n_stages`` copies of ``stage_fn`` as a pipeline.

    Parameters
    ----------
    stage_fn : (stage_params, activation) -> activation, shape-preserving
    stacked_params : pytree whose leaves have leading axis ``n_stages``
        (stage i's weights at index i) — sharded over ``axis_name``
    x_microbatches : (M, microbatch, ...) array, replicated
    mesh : jax.sharding.Mesh containing ``axis_name``
    Returns the last stage's outputs, (M, microbatch, ...), replicated.
    """
    from ..analysis.collective_check import check_axis, check_ppermute
    from .. import sharding as _sharding

    mesh = _sharding.as_jax_mesh(mesh)
    check_axis(mesh, axis_name, op="gpipe")
    n = mesh.shape[axis_name]
    m = x_microbatches.shape[0]
    if n < 2:
        raise MXNetError("CC604 (pipeline-schedule-mismatch): gpipe needs "
                         "a pipeline axis of size >= 2")
    if m < 1:
        raise MXNetError("CC604 (pipeline-schedule-mismatch): gpipe needs "
                         "at least one microbatch (x_microbatches has "
                         "leading dim 0)")
    # every check above/below uses static shape metadata only — gpipe runs
    # under jax.grad, so the arrays themselves may be tracers
    bad = [tuple(p.shape) for p in jax.tree_util.tree_leaves(stacked_params)
           if hasattr(p, "shape") and (p.ndim == 0 or p.shape[0] != n)]
    if bad:
        raise MXNetError(
            "CC604 (pipeline-schedule-mismatch): stacked_params leaves "
            "must have leading axis n_stages=%d (the %r mesh axis); got "
            "leaf shapes %s" % (n, axis_name, bad))
    perm = [(i, i + 1) for i in range(n - 1)]  # last stage keeps its output
    check_ppermute(mesh, axis_name, perm, op="gpipe")

    def per_device(params_local, xs):
        # shard_map gives each device a leading-axis slice of size 1
        params = jax.tree_util.tree_map(lambda p: p[0], params_local)
        idx = lax.axis_index(axis_name)
        state0 = jnp.zeros(xs.shape[1:], xs.dtype)

        def tick(state, t):
            x_t = xs[jnp.clip(t, 0, m - 1)]
            inp = jnp.where(idx == 0, x_t, state)
            out = stage_fn(params, inp)
            nxt = lax.ppermute(out, axis_name, perm)
            return nxt, out

        _, outs = lax.scan(tick, state0, jnp.arange(m + n - 1))
        # the LAST stage's outputs for microbatch j appear at tick
        # j + (n-1); zero on every other device, then psum-replicate
        mine = lax.dynamic_slice_in_dim(outs, n - 1, m, axis=0)
        # select, don't multiply: dead-lane ticks run stage_fn on zero
        # bootstrap state, and 0 * NaN would leak NaN through the psum
        mine = jnp.where(idx == n - 1, mine, jnp.zeros_like(mine))
        return lax.psum(mine, axis_name)

    from .mesh import shard_map

    return shard_map(
        per_device, mesh=mesh,
        in_specs=(P(axis_name), P()), out_specs=P(),
        check_vma=False,
    )(stacked_params, x_microbatches)


def gpipe_loss_fn(stage_fn, loss_fn, mesh, axis_name="pp"):
    """Compose a differentiable pipelined loss:
    ``f(stacked_params, x_microbatches, y_microbatches) -> scalar``.
    Gradients (via ``jax.grad``) run the reverse pipeline automatically.
    """

    def f(stacked_params, x_mb, y_mb):
        outs = gpipe(stage_fn, stacked_params, x_mb, mesh, axis_name)
        return loss_fn(outs, y_mb)

    return f


# ---------------------------------------------------------------------------
# Non-identical stages: host-scheduled GPipe with per-stage placement
# ---------------------------------------------------------------------------


class HostPipeline:
    """GPipe over NON-identical stages (embedding/blocks/head included).

    The SPMD ``gpipe`` above needs one shape-preserving stage replicated
    on every device; real models are not shaped like that.  This runtime
    instead keeps each stage a separately jitted callable whose
    parameters LIVE on that stage's device, and drives the microbatch
    schedule from the host: JAX's async dispatch overlaps stage s's
    microbatch j with stage s+1's microbatch j-1 automatically, and the
    backward recomputes each stage's forward inside its vjp (classic
    GPipe activation rematerialisation — per-device memory holds one
    stage's weights + boundary activations only).

    Parameters
    ----------
    stage_fns : list of pure callables ``(params, activation) -> activation``
        (the LAST stage returns the model output fed to ``loss_fn``)
    stage_params : list of param pytrees, one per stage (``shared_params``
        index groups additionally require each stage's params to be a
        FLAT list of arrays, which is what ``partition_llama`` produces)
    loss_fn : ``(output, labels) -> scalar`` (mean over the microbatch)
    devices : optional list of jax devices, one per stage (defaults to
        ``jax.devices()[:n_stages]``)
    """

    def __init__(self, stage_fns, stage_params, loss_fn, devices=None,
                 shared_params=(), param_rule=None, data_axis="data"):
        if len(stage_fns) != len(stage_params):
            raise MXNetError("one params pytree per stage required")
        self.n_stages = len(stage_fns)
        self.loss_fn = loss_fn
        # groups of (stage, leaf_index) aliases of ONE logical parameter
        # (tied embeddings): grads are summed across the group and every
        # member receives the identical update
        self.shared_params = [list(g) for g in shared_params]
        if devices is None:
            devices = jax.devices()[: self.n_stages]
        if len(devices) < self.n_stages:
            raise MXNetError("need >= n_stages devices")
        self.devices = list(devices[: self.n_stages])
        # 3D parallelism: an entry in ``devices`` may be a
        # ``jax.sharding.Mesh`` instead of a single device — that stage
        # then runs dp×tp-sharded via GSPMD (params placed by
        # ``param_rule(name=None, shape)``→PartitionSpec, activations
        # batch-sharded over ``data_axis``), while the host schedule
        # still pipelines stages: pp across meshes, dp×tp within each.
        self._param_rule = param_rule
        self._data_axis = data_axis
        self.params = [
            jax.tree_util.tree_map(
                lambda a, d=dev: self._put_param(jnp.asarray(a), d), p)
            for p, dev in zip(stage_params, self.devices)]
        self._fwd = [jax.jit(f) for f in stage_fns]

        def _mid_bwd(f):
            def run(p, a, g):
                _, vjp = jax.vjp(f, p, a)
                return vjp(g)
            return jax.jit(run)

        self._bwd = [_mid_bwd(f) for f in stage_fns[:-1]]
        f_last = stage_fns[-1]

        def _last_grad(p, a, y):
            loss, grads = jax.value_and_grad(
                lambda p_, a_: loss_fn(f_last(p_, a_), y),
                argnums=(0, 1))(p, a)
            return loss, grads[0], grads[1]

        self._last_grad = jax.jit(_last_grad)

    # -- placement helpers (single device OR dp×tp mesh per stage) --------
    def _put_param(self, arr, dev):
        from jax.sharding import Mesh, NamedSharding

        if isinstance(dev, Mesh):
            spec = self._param_rule(None, arr.shape) \
                if self._param_rule else None
            return jax.device_put(
                arr, NamedSharding(dev, spec if spec is not None else P()))
        return jax.device_put(arr, dev)

    def _put_act(self, arr, stage):
        from jax.sharding import Mesh, NamedSharding

        dev = self.devices[stage]
        if isinstance(dev, Mesh):
            # batch-shard activations over the stage's data axis when it
            # exists and divides the batch; replicate otherwise
            spec = P()
            if self._data_axis in dev.shape and arr.ndim >= 1 and \
                    arr.shape[0] % dev.shape[self._data_axis] == 0:
                spec = P(self._data_axis)
            return jax.device_put(arr, NamedSharding(dev, spec))
        return jax.device_put(arr, dev)

    def forward_backward(self, x_microbatches, y_microbatches):
        """Returns (mean loss over microbatches, per-stage grads)."""
        n = self.n_stages
        m = len(x_microbatches)
        if m != len(y_microbatches):
            raise MXNetError(
                "CC604 (pipeline-schedule-mismatch): %d x microbatches "
                "but %d y microbatches — the schedule would silently "
                "truncate to the shorter list" % (m, len(y_microbatches)))
        if m < 1:
            raise MXNetError("CC604 (pipeline-schedule-mismatch): need at "
                             "least one microbatch")
        acts = [[None] * m for _ in range(n)]  # stage input per mb
        for j, x in enumerate(x_microbatches):
            acts[0][j] = self._put_act(jnp.asarray(x), 0)
            for s in range(n - 1):
                out = self._fwd[s](self.params[s], acts[s][j])
                acts[s + 1][j] = self._put_act(out, s + 1)
        grads = [None] * n
        losses = []
        for j in range(m):
            y = self._put_act(jnp.asarray(y_microbatches[j]), n - 1)
            loss, gp, ga = self._last_grad(self.params[-1],
                                           acts[-1][j], y)
            losses.append(loss)
            grads[-1] = gp if grads[-1] is None else jax.tree_util.tree_map(
                jnp.add, grads[-1], gp)
            g = ga
            for s in range(n - 2, -1, -1):
                g = self._put_act(g, s)
                gp, ga = self._bwd[s](self.params[s], acts[s][j], g)
                grads[s] = gp if grads[s] is None else \
                    jax.tree_util.tree_map(jnp.add, grads[s], gp)
                g = ga
        inv = 1.0 / m
        grads = [jax.tree_util.tree_map(lambda a: a * inv, g)
                 for g in grads]
        loss = sum(float(l) for l in losses) / m
        return loss, grads

    def _merge_shared_grads(self, grads):
        """Sum gradients of aliased (tied) parameters across stages."""
        for group in self.shared_params:
            total = None
            for (s, i) in group:
                g = self._put_param(grads[s][i],
                                    self.devices[group[0][0]])
                total = g if total is None else total + g
            for (s, i) in group:
                grads[s][i] = self._put_param(total, self.devices[s])
        return grads

    def sgd_step(self, x_microbatches, y_microbatches, lr=0.1):
        """One pipelined train step with in-place SGD; returns the loss.
        Tied parameters (``shared_params`` groups) receive one summed
        update so the aliases never diverge."""
        loss, grads = self.forward_backward(x_microbatches,
                                            y_microbatches)
        if self.shared_params:
            grads = self._merge_shared_grads([list(g) for g in grads])
        self.params = [
            jax.tree_util.tree_map(lambda p, g: p - lr * g, ps, gs)
            for ps, gs in zip(self.params, grads)]
        return loss


def partition_llama(model, n_stages):
    """Split a gluon ``LlamaModel`` into ``n_stages`` NON-identical
    pipeline stages (embedding fused into stage 0, final norm + LM head
    into the last).  Returns ``(stage_fns, stage_params, param_refs,
    shared_groups)``.

    ``param_refs[s]`` lists the gluon Parameters backing stage ``s`` (in
    the order the stage fn expects), so updated weights can be synced
    back with ``Parameter.set_data``.  The fourth return value lists
    shared-parameter alias groups (tied embeddings appear in stage 0 AND
    the last stage) — pass it to ``HostPipeline(shared_params=...)`` so
    tied weights receive one summed update.
    """
    from ..gluon import block as _block_mod
    from ..ndarray.ndarray import NDArray

    for p in model.collect_params().values():
        if p._data is None:
            raise MXNetError(
                "partition_llama: run one forward first to resolve "
                "deferred parameter shapes (param %s unresolved)" % p.name)
    blocks = list(model.blocks._children.values())
    if n_stages < 2 or n_stages > len(blocks):
        raise MXNetError("need 2 <= n_stages <= n_blocks")
    per = [len(blocks) // n_stages] * n_stages
    for i in range(len(blocks) % n_stages):
        per[i] += 1
    segments, start = [], 0
    for s, k in enumerate(per):
        segs = blocks[start:start + k]
        start += k
        segments.append(segs)

    def params_of(gluon_blocks):
        out = []
        for b in gluon_blocks:
            out.extend(b.collect_params().values())
        return out

    head_blocks = [model.norm] + (
        [] if model._tie else [model.lm_head])
    stage_blocks = []
    for s, segs in enumerate(segments):
        pre = [model.embed] if s == 0 else []
        post = head_blocks if s == n_stages - 1 else []
        stage_blocks.append(pre + segs + post)

    def make_fn(gluon_blocks, prefs, is_last):
        tie = model._tie and is_last

        def fn(param_arrays, act):
            with _block_mod._functional_params(prefs, param_arrays) as st:
                x = NDArray(act)
                for b in gluon_blocks:
                    x = b._forward_imperative(x)
                if tie:
                    w = st.param_map[id(model.embed.weight)]
                    x = NDArray(x.data() @ w.data().T)
                return x.data()
        return fn

    stage_fns, stage_params, param_refs = [], [], []
    for s, gblocks in enumerate(stage_blocks):
        prefs = params_of(gblocks)
        if model._tie and s == n_stages - 1:
            prefs = prefs + [model.embed.weight]
        param_refs.append(prefs)
        stage_params.append([p.data().data() for p in prefs])
        stage_fns.append(make_fn(gblocks, prefs, s == n_stages - 1))
    by_param = {}
    for s, prefs in enumerate(param_refs):
        for i, p in enumerate(prefs):
            by_param.setdefault(id(p), []).append((s, i))
    shared = [g for g in by_param.values() if len(g) > 1]
    return stage_fns, stage_params, param_refs, shared
