"""Megatron-style tensor-parallel sharding rules for JitTrainStep.

The reference's model parallelism was manual per-layer device placement
(``ctx_group``; docs/static_site …/model_parallel_lstm.md).  TPU-native
replacement: declarative PartitionSpec rules consumed by
``JitTrainStep(param_rule=...)`` — GSPMD inserts the Megatron
communication pattern (all-gather after column layers, reduce-scatter /
all-reduce after row layers) automatically from the weight shardings
alone (Shoeybi et al. 2019's column/row pairing, expressed as shardings).

Two layers of API:

- :func:`pattern_rule` — generic glob-pattern → PartitionSpec mapping.
- :func:`megatron_rule` — the canonical transformer pairing: QKV and MLP
  up/gate projections column-parallel (output dim sharded), attention
  output and MLP down projections row-parallel (input dim sharded),
  embeddings vocab-sharded, everything else replicated.  Works out of the
  box for the model-zoo ``llama``/``bert`` naming; pass extra patterns
  for custom nets.

Every rule degrades safely: a dim that does not divide the mesh axis is
replicated instead (GSPMD requires divisibility for even sharding).
"""
from __future__ import annotations

import fnmatch

from jax.sharding import PartitionSpec as P

from .. import sharding as _sharding

__all__ = ["pattern_rule", "megatron_rule",
           "COLUMN_PATTERNS", "ROW_PATTERNS", "EMBED_PATTERNS"]

# Dense weights are stored (out_features, in_features) — reference layout
# (src/operator/nn/fully_connected.cc) — so "column parallel" = shard dim
# 0 and "row parallel" = shard dim 1.
COLUMN_PATTERNS = (
    "*attn_q_weight", "*attn_k_weight", "*attn_v_weight",
    "*query_weight", "*key_weight", "*value_weight",
    "*ffn_gate_weight", "*ffn_up_weight", "*fc1_weight",
    "*inter_weight", "*head_weight",
)
ROW_PATTERNS = (
    "*attn_o_weight", "*out_proj_weight", "*proj_weight",
    "*ffn_down_weight", "*fc2_weight", "*outmap_weight",
)
EMBED_PATTERNS = ("*embed_weight", "*embedding0_weight", "*word_embed*")


def _axis_size(mesh, axis):
    """Total mesh extent for a spec entry: a name or a tuple of names
    (tuple axes multiply, e.g. fsdp+tp sharding one dim over both)."""
    try:
        if isinstance(axis, (tuple, list)):
            size = 1
            for a in axis:
                size *= mesh.shape[a]
            return size
        return mesh.shape[axis]
    except Exception:
        return None


def pattern_rule(patterns, mesh=None, default=None):
    """Build a ``param_rule`` from ``[(glob, PartitionSpec), ...]``.

    First matching glob wins.  When ``mesh`` is given, a spec whose named
    axes do not evenly divide the corresponding dim is replaced by
    ``default`` (replication) instead of failing inside GSPMD.

    ``mesh`` may be a ``sharding.Mesh``, a raw jax mesh, or an axes
    dict; ``None`` picks up the ambient mesh (``with Mesh(...):`` /
    ``mx.tpu(mesh=...)``) when one is active.
    """
    pats = list(patterns)
    if mesh is None:
        mesh = _sharding.current_mesh()
    mesh = _sharding.as_jax_mesh(mesh)

    def rule(name, shape):
        for pat, spec in pats:
            if fnmatch.fnmatch(name, pat):
                if mesh is not None and spec is not None:
                    for d, ax in enumerate(spec):
                        if ax is None:
                            continue
                        size = _axis_size(mesh, ax)
                        if size and (d >= len(shape)
                                     or shape[d] % size != 0):
                            return default
                return spec
        return default

    return rule


def megatron_rule(axis="model", mesh=None, extra=(),
                  shard_embeddings=True):
    """The canonical transformer column/row pairing as a param_rule.

    Parameters
    ----------
    axis : mesh axis name carrying tensor parallelism
    mesh : optional Mesh for divisibility degradation (strongly
        recommended — GQA KV heads often don't divide large tp degrees)
    extra : additional ``(glob, PartitionSpec)`` pairs, tried first
    shard_embeddings : vocab-shard embedding/head tables (dim 0)
    """
    pairs = list(extra)
    pairs += [(p, P(axis, None)) for p in COLUMN_PATTERNS]
    pairs += [(p, P(None, axis)) for p in ROW_PATTERNS]
    if shard_embeddings:
        pairs += [(p, P(axis, None)) for p in EMBED_PATTERNS]
    return pattern_rule(pairs, mesh=mesh)
