"""Ring attention: sequence/context parallelism over the ICI ring.

No reference counterpart (SURVEY §5.7 — the reference never sharded the
sequence axis); designed TPU-first: Q stays resident per chip while K/V
blocks travel the ring via ``lax.ppermute``, each hop overlapping the
next transfer with the current block's flash-style online-softmax
accumulation.  Communication per step is O(T/n · D) on ICI and the full
(T, T) score matrix never exists on any chip — sequences scale linearly
with ring size.

Usage: inside ``shard_map`` (``ring_attention_sharded`` wraps this), with
q/k/v sharded on the sequence axis across ``axis_name``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _block_scores(q, k, scale, causal, q_off, k_off):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        row = q_off + jnp.arange(q.shape[1])[:, None]
        col = k_off + jnp.arange(k.shape[1])[None, :]
        s = jnp.where(col <= row, s, -jnp.inf)
    return s


def ring_attention(q, k, v, axis_name, scale=None, causal=False):
    """Per-shard body: q/k/v (B, T_local, D) — call inside shard_map.

    Online-softmax accumulation over ring hops; each hop ppermutes the
    (K, V) pair one step around the ring.
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    t_local, d = q.shape[1], q.shape[2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_off = my * t_local
    m0 = jnp.full(q.shape[:2] + (1,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros(q.shape[:2] + (1,), jnp.float32)
    acc0 = jnp.zeros(q.shape[:2] + (d,), jnp.float32)

    def step(i, carry):
        k_cur, v_cur, m, l, acc = carry
        # the block we hold at hop i originated on rank (my - i) mod n
        src = (my - i) % n
        s = _block_scores(q, k_cur, scale, causal, q_off, src * t_local)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        # all -inf rows (fully masked block): keep m to avoid NaNs
        m_new = jnp.where(jnp.isinf(m_new) & (m_new < 0), m, m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(jnp.isnan(p), 0.0, p)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bqk,bkd->bqd", p, v_cur.astype(jnp.float32))
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m_new, l_new, acc_new

    carry = (k, v, m0, l0, acc0)
    for i in range(n):  # static unroll: n is the mesh axis size
        carry = step(i, carry)
    _, _, m, l, acc = carry
    safe_l = jnp.where(l == 0, 1.0, l)
    return (acc / safe_l).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", scale=None,
                           causal=False):
    """Shard (B, T, D) [or (B, H, T, D)] on the sequence axis and run
    ring attention over ``axis_name`` of ``mesh``."""
    from jax.sharding import PartitionSpec as P

    from .mesh import shard_map
    from ..analysis.collective_check import check_axis
    from .. import sharding as _sharding

    mesh = _sharding.as_jax_mesh(mesh)
    check_axis(mesh, axis_name, op="ring_attention_sharded")
    four_d = q.ndim == 4
    if four_d:
        b, h, t, d = q.shape
        q = q.reshape(b * h, t, d)
        k = k.reshape(b * h, k.shape[2], d)
        v = v.reshape(b * h, v.shape[2], d)

    spec = P(None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          scale=scale, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    out = fn(q, k, v)
    if four_d:
        out = out.reshape(b, h, t, d)
    return out
